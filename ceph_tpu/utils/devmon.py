"""Device-runtime observability: the monitor every jit entry point
reports through (round 14).

The device runtime is a first-class observed subsystem now, the same
way round 8 made ops and round 12 made counters observable. Three
blind spots motivated it:

- **silent kernel-path degradation**: a daemon that loses its fused
  Pallas plan serves CRUSH ~34x slower with zero signal — until now
  the only detector was a bench run's ``path_expected_vs_actual`` row
  (round 10), which a production daemon never executes;
- **invisible jit compiles**: a recompile (shape instability, plan
  rebuild) stalls the shared event loop for seconds — round 12 had to
  stall-clamp mgr liveness around exactly this without ever being able
  to SEE the compile that caused it;
- **unaccounted transfers**: H2D staging and D2H readbacks dominate
  wall time on tunnel-attached devices, and nothing counted the bytes.

Two kinds of :class:`DeviceRuntimeMonitor` exist:

- the **process singleton** (``devmon()``, counter family
  ``device_runtime``, registered in the process collection): the
  compile/transfer side. Process-level code — ``crush.mapper``,
  ``crush.sharded_sweep``, ``ec.jax_plugin`` — reports here, because
  the jit caches it observes are process-wide. A daemon's Tracer can
  be attached (:meth:`attach_tracer`) so each first-compile emits a
  deterministic ``jit_compile`` span (never sampled away — compiles
  are rare, operator-critical events) that ships monward on the
  daemon's existing report piggyback and lands in ``trace ls/show``.
- **per-daemon instances** (``register=False``, counter family
  ``devmon``, reaching ``/metrics`` only through the daemon's
  MMgrReport session — the round-13 ``osd_ec_agg`` discipline): the
  kernel-path health side. Every ``Mapper``/``OSDMapMapping`` sweep
  site records which engine actually ran (:meth:`record_launch`) and
  whether it matched the expectation (:meth:`record_path_check`):
  ``devmon_expected_engine`` pins the operator's deployed expectation
  ("this daemon runs pallas"), ``auto`` trusts the built plan so the
  only mismatch is a plan that silently degraded mid-run.

Cluster surfacing: counters flow through the existing
MgrReporter -> DaemonStateIndex -> prometheus leg as dedicated
``ceph_device_*`` rows; the cumulative (checks, mismatches, compiles,
transfer bytes) piggyback monward on MPGStats (``device_health``), the
mon debounces per-report mismatch rates into the
**KERNEL_PATH_DEGRADED** health check (``mon_kernel_path_*`` knobs,
same confirm/clear discipline as OSD_SLOW), and
``ceph device-runtime status`` serves the per-daemon table.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.utils.perf_counters import PerfCountersBuilder

# engines a path string can resolve to ("+sharded" is a suffix, not an
# engine: the sharded sweep serves whichever engine the single-device
# path would)
ENGINES = ("pallas", "xla", "scalar")

# warm-set bound: (fn, key) pairs tracked for first-call compile
# detection. Shape churn past this evicts the OLDEST entry only, so a
# long-running daemon's hot paths stay warm (a full clear would
# re-count every hot path's next call as a fresh compile).
_WARM_MAX = 4096

# device fault injection (round 16): jit_call is the one chokepoint
# every jit-backed device call passes through, so it is also where
# sim.faults' device kinds (jit_fail / jit_stall / bad_result) fire.
# Installed process-wide by Cluster.install_faults; None in production.
_fault_injector = None


def set_fault_injector(inj) -> None:
    """Attach (or detach, with None) the process's FaultInjector to
    the jit_call chokepoint. The injector is consulted only when it
    has device rules installed — the no-faults fast path costs one
    attribute read."""
    global _fault_injector
    _fault_injector = inj


def _corrupt_result(out):
    """The ``bad_result`` fault: flip one element of the returned
    array (first element of a tuple result — the payload; EC's crc
    sidecar rides along untouched so checksum verification still
    sees the corrupt payload). Returns a host copy; shapes/dtypes
    are preserved so only bit-exact checks can tell."""
    import numpy as np
    if isinstance(out, tuple):
        if not out:
            return out
        return (_corrupt_result(out[0]),) + tuple(out[1:])
    try:
        arr = np.array(out)
    except Exception:
        return out
    if arr.size == 0:
        return out
    flat = arr.reshape(-1)
    if arr.dtype.kind in "iu":
        flat[0] ^= 1
    elif arr.dtype.kind == "f":
        flat[0] = flat[0] + 1.0
    else:
        return out
    return arr


def normalize_engine(path: str | None) -> str:
    """Collapse a mapping path to its base engine:
    'pallas-interpret' -> 'pallas', 'xla+sharded' -> 'xla'."""
    if not path:
        return "?"
    base = path.split("+", 1)[0]
    if base.startswith("pallas"):
        return "pallas"
    return base if base in ENGINES else "?"


class DeviceRuntimeMonitor:
    """Compile accounting + kernel-path health + transfer gauges.

    ``register=True`` puts the counter family in the process-wide
    collection (the ``devmon()`` singleton); per-daemon instances pass
    ``register=False`` and reach `/metrics` only through their report
    session. ``config`` is the owning daemon's LIVE config dict —
    ``devmon_expected_engine`` is read per check, so a runtime flip
    applies to the next sweep."""

    def __init__(self, name: str = "device_runtime",
                 register: bool = True,
                 config: dict | None = None):
        self.config = config if config is not None else {}
        self.perf = (
            PerfCountersBuilder(name)
            .add_u64_counter("jit_compiles",
                             "first-call jit compiles observed (per "
                             "distinct function + abstract shape key)")
            .add_time("jit_compile_seconds",
                      "wall seconds spent in compile-triggering first "
                      "calls")
            .add_u64_counter("launches_pallas",
                             "map/sweep launches served by the fused "
                             "Pallas kernel (interpret included)")
            .add_u64_counter("launches_xla",
                             "map/sweep launches served by the XLA "
                             "rule VM")
            .add_u64_counter("launches_scalar",
                             "map/sweep launches served by the scalar "
                             "spec walk (legacy tunables)")
            .add_u64_counter("launches_sharded",
                             "launches that rode the mesh-sharded "
                             "path (counted in addition to the engine)")
            .add_u64_counter("path_checks",
                             "expected-vs-actual engine checks at "
                             "Mapper/OSDMapMapping sweep sites")
            .add_u64_counter("path_mismatch",
                             "sweeps whose actual engine differed "
                             "from the expected one (the silent-"
                             "degradation signal)")
            .add_u64_counter("h2d_bytes",
                             "host->device bytes staged (mapper "
                             "packing, EC pipeline ingest)")
            .add_u64_counter("d2h_bytes",
                             "device->host bytes read back")
            .add_u64("device_bytes_staged",
                     "bytes of the most recent staging op (gauge)")
            .add_u64("device_bytes_watermark",
                     "largest single staging op seen (gauge, "
                     "monotone max)")
            .add_u64_counter("quarantine_entries",
                             "kernel-path quarantine entries (a device "
                             "failure benched the fused kernel)")
            .add_u64_counter("quarantine_exits",
                             "kernel-path re-promotions (a bit-exact "
                             "probe passed and the kernel serves again)")
            .add_u64_counter("quarantine_probes",
                             "backoff re-probe attempts against a "
                             "quarantined kernel")
            .add_u64_counter("quarantine_probe_failures",
                             "re-probes that raised or mismatched the "
                             "serving path bit-exactly")
            .add_u64("quarantined_now",
                     "kernels currently quarantined (serving the "
                     "fallback engine, re-probe pending; gauge)")
            .add_u64("reprobing_now",
                     "quarantined kernels past their first failed "
                     "re-probe (gauge)")
            .add_u64("quarantine_permanent_now",
                     "kernels permanently disabled after "
                     "crush_kernel_reprobe_disable_after consecutive "
                     "failures (gauge)")
            .add_u64_counter("faults_injected",
                             "device faults fired at the jit_call "
                             "chokepoint (sim.faults device kinds)")
            .add_u64_counter("stream_fallbacks",
                             "streaming-encode pipelines that fell "
                             "back to the unpipelined path")
            .create_perf_counters(register=register))
        self.tracer = None           # utils.tracing.Tracer | None
        self._lock = threading.Lock()
        # insertion-ordered: eviction at _WARM_MAX pops oldest only
        self._warm: dict[tuple, None] = {}
        # fn name -> {count, seconds, last_key, last_seconds}
        self.functions: dict[str, dict] = {}
        self._watermark = 0
        self.last_mismatch: dict | None = None
        # quarantine token -> "quarantined"|"reprobing"|"permanent"
        self._quarantine: dict = {}

    # -- wiring ------------------------------------------------------------
    def attach_tracer(self, tracer) -> None:
        """Attach the owning daemon's Tracer: every first compile
        emits one deterministic ``jit_compile`` span through it (in
        multi-daemon test processes the last attach wins — one span
        per compile either way, never zero, never double)."""
        self.tracer = tracer

    # -- compile accounting ------------------------------------------------
    def jit_call(self, fn_name: str, key, fn, *args):
        """Run ``fn(*args)``, recording the call as a jit compile when
        this (fn_name, key) pair has never run before. ``key`` must
        capture the jit cache identity — callers pass (id(jitted_fn),
        abstract shape), so a process-shared lru'd program is warm
        across Mapper instances while a per-Mapper kernel wrapper is
        cold once per Mapper. Warm calls cost one set lookup; a failed
        first call un-warms so the retry path's compile still counts.

        This is also the device-fault injection chokepoint: when a
        FaultInjector with device rules is attached
        (:func:`set_fault_injector`), its verdict runs first —
        ``jit_stall`` sleeps here, ``jit_fail`` raises before any
        warm-set bookkeeping (so a later retry still counts its
        compile), ``bad_result`` corrupts the completed result."""
        corrupt = False
        inj = _fault_injector
        if inj is not None and inj.has_device_rules():
            stall, fail, corrupt = inj.device_verdicts(
                fn_name, str(key))
            if stall > 0:
                time.sleep(stall)
            if fail:
                self.perf.inc("faults_injected")
                raise RuntimeError(
                    f"injected device fault: jit_fail on {fn_name}")
        k = (fn_name, key)
        with self._lock:
            warm = k in self._warm
            if not warm:
                if len(self._warm) >= _WARM_MAX:
                    self._warm.pop(next(iter(self._warm)))
                self._warm[k] = None
        if warm:
            out = fn(*args)
        else:
            t0 = time.perf_counter()
            try:
                out = fn(*args)
            except BaseException:
                with self._lock:
                    self._warm.pop(k, None)
                raise
            self.record_compile(fn_name, key,
                                time.perf_counter() - t0)
        if corrupt:
            self.perf.inc("faults_injected")
            out = _corrupt_result(out)
        return out

    def record_compile(self, fn_name: str, key, seconds: float) -> None:
        """One observed compile: counter + time sum + per-function
        table + (when a tracer is attached) a deterministic
        ``jit_compile`` span whose wall covers the first call."""
        seconds = max(float(seconds), 0.0)
        self.perf.inc("jit_compiles")
        self.perf.tinc("jit_compile_seconds", seconds)
        with self._lock:
            ent = self.functions.setdefault(
                fn_name, {"count": 0, "seconds": 0.0})
            ent["count"] += 1
            ent["seconds"] = round(ent["seconds"] + seconds, 6)
            ent["last_key"] = str(key)[:120]
            ent["last_seconds"] = round(seconds, 6)
        tracer = self.tracer
        if tracer is not None:
            # a real Span, but assembled post-hoc: the trace id is
            # minted directly (head sampling must not drop compile
            # evidence) and the start is back-dated so the span's
            # wall IS the measured first-call stall
            from ceph_tpu.utils.tracing import Span, new_trace_id
            s = Span(tracer, "jit_compile", new_trace_id(),
                     tags={"fn": fn_name, "key": str(key)[:120]})
            s.start -= seconds
            s.duration = seconds
            s.finished = True
            tracer.record(s)

    # -- kernel-path health ------------------------------------------------
    def expected_engine(self, plan_path: str | None) -> str:
        """The engine this monitor's owner EXPECTS sweeps to run on:
        the ``devmon_expected_engine`` knob when pinned, else the
        plan's own prediction (``plan_path``) — under which the only
        possible mismatch is a plan that degraded mid-run."""
        want = str(self.config.get("devmon_expected_engine", "auto"))
        if want in ("", "auto"):
            return normalize_engine(plan_path)
        return want

    def record_launch(self, path: str | None, n: int = 1) -> None:
        """Count a map/sweep launch by the engine that actually ran."""
        eng = normalize_engine(path)
        if eng in ENGINES:
            self.perf.inc(f"launches_{eng}", n)
        if path and "+sharded" in path:
            self.perf.inc("launches_sharded", n)

    def record_path_check(self, expected: str | None,
                          actual: str | None) -> bool:
        """One expected-vs-actual engine check; returns True on
        mismatch. ``expected`` may be a raw path or a bare engine;
        both sides normalize, so 'pallas-interpret' == 'pallas' and
        the '+sharded' suffix never trips a false mismatch."""
        e, a = normalize_engine(expected), normalize_engine(actual)
        self.perf.inc("path_checks")
        if e == a or e == "?":
            return False
        self.perf.inc("path_mismatch")
        self.last_mismatch = {"expected": e, "actual": a,
                              "stamp": time.time()}
        return True

    def record_sweep(self, plan_path: str | None, actual: str | None,
                     n_launches: int = 1) -> bool:
        """The per-sweep-site combo: launch counter + expectation
        check (knob-pinned or plan-trusted)."""
        self.record_launch(actual, n_launches)
        return self.record_path_check(
            self.expected_engine(plan_path), actual)

    # -- kernel quarantine (round 16) --------------------------------------
    def set_quarantine_state(self, token, state: str | None) -> None:
        """Track one kernel owner's quarantine state (keyed by an
        opaque token — Mappers use their per-incarnation devmon
        token). ``None`` clears. The three gauges always reflect the
        live table."""
        with self._lock:
            if state is None:
                self._quarantine.pop(token, None)
            else:
                self._quarantine[token] = state
            states = list(self._quarantine.values())
        self.perf.set("quarantined_now",
                      sum(1 for s in states
                          if s in ("quarantined", "reprobing")))
        self.perf.set("reprobing_now",
                      sum(1 for s in states if s == "reprobing"))
        self.perf.set("quarantine_permanent_now",
                      sum(1 for s in states if s == "permanent"))

    def record_quarantine_enter(self, token,
                                state: str = "quarantined") -> None:
        self.perf.inc("quarantine_entries")
        self.set_quarantine_state(token, state)

    def record_quarantine_exit(self, token) -> None:
        self.perf.inc("quarantine_exits")
        self.set_quarantine_state(token, None)

    def record_probe(self, ok: bool) -> None:
        self.perf.inc("quarantine_probes")
        if not ok:
            self.perf.inc("quarantine_probe_failures")

    # -- transfers / memory ------------------------------------------------
    def record_h2d(self, nbytes: int) -> None:
        if nbytes > 0:
            self.perf.inc("h2d_bytes", int(nbytes))

    def record_d2h(self, nbytes: int) -> None:
        if nbytes > 0:
            self.perf.inc("d2h_bytes", int(nbytes))

    def note_staging(self, nbytes: int) -> None:
        """One staging op's device-resident footprint: the gauge holds
        the most recent op, the watermark the largest ever (per-op
        max, NOT a running sum — frees are not tracked, and a
        cumulative gauge would be a lie)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        self.perf.set("device_bytes_staged", nbytes)
        with self._lock:
            if nbytes > self._watermark:
                self._watermark = nbytes
        self.perf.set("device_bytes_watermark", self._watermark)

    # -- views -------------------------------------------------------------
    def mismatch_ratio(self) -> float:
        d = self.perf.dump()
        checks = int(d.get("path_checks", 0))
        return (int(d.get("path_mismatch", 0)) / checks) if checks \
            else 0.0

    def health_report(self) -> dict[str, int]:
        """The MPGStats ``device_health`` piggyback payload: this
        monitor's cumulative path health merged with the process
        singleton's compile/transfer side (one daemon per process in
        production, so the merge IS the daemon's view). All u64."""
        d = self.perf.dump()
        proc = self if self is _singleton else devmon()
        p = proc.perf.dump() if proc is not self else d
        return {
            "checks": int(d.get("path_checks", 0)),
            "mismatches": int(d.get("path_mismatch", 0)),
            "launches_pallas": int(d.get("launches_pallas", 0)),
            "launches_xla": int(d.get("launches_xla", 0)),
            "launches_scalar": int(d.get("launches_scalar", 0)),
            "launches_sharded": int(d.get("launches_sharded", 0)),
            "compiles": int(p.get("jit_compiles", 0)),
            "compile_ms": int(
                float(p.get("jit_compile_seconds", 0.0)) * 1e3),
            "h2d_bytes": int(p.get("h2d_bytes", 0)),
            "d2h_bytes": int(p.get("d2h_bytes", 0)),
            # quarantine lives process-side (Mappers are process-level)
            "quarantined": int(p.get("quarantined_now", 0)),
            "reprobing": int(p.get("reprobing_now", 0)),
            "quarantine_permanent": int(
                p.get("quarantine_permanent_now", 0)),
            "quarantine_entries": int(p.get("quarantine_entries", 0)),
            "quarantine_exits": int(p.get("quarantine_exits", 0)),
        }

    def dump(self) -> dict:
        """The asok ``device`` block / ``device-runtime status``
        payload for this monitor."""
        import jax
        out = {
            "engine": jax.default_backend(),
            "expected_engine": str(
                self.config.get("devmon_expected_engine", "auto")),
            "counters": self.perf.dump(),
            "mismatch_ratio": round(self.mismatch_ratio(), 4),
        }
        if self.last_mismatch:
            out["last_mismatch"] = dict(self.last_mismatch)
        with self._lock:
            if self.functions:
                out["compiles_by_fn"] = {
                    k: dict(v) for k, v in sorted(self.functions.items())}
        return out


_singleton: DeviceRuntimeMonitor | None = None


def engine_name() -> str:
    """The process's default jax backend ('cpu'/'tpu'/...) — the
    `device_engine` field daemons stamp on their reports."""
    import jax
    return str(jax.default_backend())


def devmon() -> DeviceRuntimeMonitor:
    """The process singleton (counter family ``device_runtime``) every
    process-level jit entry point reports through."""
    global _singleton
    if _singleton is None:
        _singleton = DeviceRuntimeMonitor()
    return _singleton
