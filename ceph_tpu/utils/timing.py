"""Readback-anchored device timing.

Why this exists: on the remote-TPU platform this sandbox provides,
``jax.Array.block_until_ready()`` returns once the *dispatch* is acknowledged
(~tens of microseconds), long before the device executes — a wall-clock loop
around it times an enqueue, not the work (round 1 shipped a 1242x-impossible
number this way). The only trustworthy anchor is data dependency: make the
host read back a value that cannot exist until every step has run.

Methodology (used by every benchmark in this repo):

1. The timed region is ONE jitted program: ``lax.fori_loop`` over S steps,
   where each step's input depends on the previous step's *full* output
   (the caller's ``step`` folds an xor-reduction of its output back into
   its carry — full, so XLA cannot dead-code-eliminate any lane).
2. The program returns a scalar derived from the final carry; the host
   timer stops only after ``np.asarray`` of that scalar — an RPC readback
   that cannot complete before execution.
3. Per-step time is the SLOPE between two step counts S_lo and S_hi:
   ``(t(S_hi) - t(S_lo)) / (S_hi - S_lo)``. The constant term (RPC floor,
   dispatch, readback, the once-per-call reduction) cancels; it is also
   reported as ``overhead_s`` so the reader can see the floor being
   subtracted (~80 ms per dispatch on this platform).

ref: replaces the wall-clock loop of
src/test/erasure-code/ceph_erasure_code_benchmark.cc (ErasureCodeBench::run),
which is sound for synchronous single-process C++ but not for an async
remote device.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

import jax


@dataclass
class ChainedTiming:
    seconds_per_step: float
    overhead_s: float            # constant term: dispatch + readback + anchor
    steps: tuple[int, int]
    totals_s: dict[int, float]   # best-of-reps total wall time per step count
    reps: int
    anchor_value: int = 0        # the scalar actually read back (proof of life)
    method: str = "chained_fori_loop_slope_readback"
    steps_executed: int = 0      # total device steps run incl. warmup
    timed_region_s: float = 0.0  # wall time of the timed (best-of) calls

    def as_dict(self) -> dict[str, Any]:
        return {
            "seconds_per_step": self.seconds_per_step,
            "overhead_s": round(self.overhead_s, 6),
            "slope_steps": list(self.steps),
            "totals_s": {str(k): round(v, 6) for k, v in self.totals_s.items()},
            "reps": self.reps,
            "steps_executed": self.steps_executed,
            "method": self.method,
        }


def xor_anchor(x: jax.Array) -> jax.Array:
    """Reduce an array to one scalar via xor — cheap, order-independent,
    consumes every lane (nothing upstream can be eliminated)."""
    import jax.numpy as jnp

    flat = x.reshape(-1)
    if flat.dtype == jnp.uint8:
        return jax.lax.reduce(flat, np.uint8(0), jax.lax.bitwise_xor, (0,))
    i32 = flat.astype(jnp.int32)
    return jax.lax.reduce(i32, np.int32(0), jax.lax.bitwise_xor, (0,))


def measure_chained(step: Callable[[Any], Any], carry0: Any,
                    anchor: Callable[[Any], jax.Array],
                    *, steps: tuple[int, int] = (2, 10),
                    reps: int = 3) -> ChainedTiming:
    """Time ``step`` (carry -> carry) with the chained-slope method.

    ``step`` MUST thread a dependency on its full previous output through
    the carry (see module docstring); ``anchor`` maps the final carry to a
    scalar that transitively depends on every step.
    """
    lo, hi = steps
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got {steps}")

    def make(n: int):
        @jax.jit
        def loop(carry):
            out = jax.lax.fori_loop(0, n, lambda i, c: step(c), carry)
            return anchor(out)
        return loop

    loops = {n: make(n) for n in (lo, hi)}
    value = 0
    executed = 0
    region = 0.0
    for n in (lo, hi):                      # compile + warm
        value = int(np.asarray(loops[n](carry0)))
        executed += n
    totals: dict[int, float] = {}
    for n in (lo, hi):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            r = loops[n](carry0)
            value = int(np.asarray(r))      # readback anchor
            dt = time.perf_counter() - t0
            best = min(best, dt)
            executed += n
            region += dt
        totals[n] = best
    per_step = (totals[hi] - totals[lo]) / (hi - lo)
    if per_step <= 0:
        # Timer noise swamped the slope (tiny workload): fall back to the
        # hi-count total divided by steps — still readback-anchored, just
        # without floor subtraction (reported method says so).
        return ChainedTiming(totals[hi] / hi, 0.0, (lo, hi), totals, reps,
                             value, "chained_fori_loop_total_readback",
                             executed, region)
    overhead = totals[lo] - lo * per_step
    return ChainedTiming(per_step, overhead, (lo, hi), totals, reps, value,
                         steps_executed=executed, timed_region_s=region)


