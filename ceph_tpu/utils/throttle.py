"""Async message throttle: bounded in-flight ops + bytes.

ref: src/common/Throttle.{h,cc} — the OSD front-door throttles
(osd_client_message_cap / osd_client_message_size_cap) that keep a
flood of client ops from swamping dispatch: excess ops queue at
admission instead of dispatching, and drain FIFO as completions free
slots. Unlike the reference's blocking get(), acquisition is an
awaitable so the admission loop — not the connection reader — bears
the backpressure.
"""

from __future__ import annotations

import asyncio
from collections import deque


class MessageThrottle:
    """Dual-budget throttle: concurrent ops and aggregate bytes.
    ``max_ops``/``max_bytes`` of 0 disable that budget. A single op
    larger than max_bytes still admits alone (never wedges)."""

    def __init__(self, max_ops: int = 0, max_bytes: int = 0):
        self.max_ops = max_ops
        self.max_bytes = max_bytes
        self.ops = 0
        self.bytes = 0
        self.peak_ops = 0
        self.waited = 0          # acquisitions that had to queue
        self._waiters: deque[asyncio.Future] = deque()

    def _would_block(self, nbytes: int) -> bool:
        if self.max_ops and self.ops >= self.max_ops:
            return True
        if self.max_bytes and self.bytes and \
                self.bytes + nbytes > self.max_bytes:
            return True
        return False

    async def acquire(self, nbytes: int = 0) -> None:
        while self._would_block(nbytes):
            fut = asyncio.get_event_loop().create_future()
            self._waiters.append(fut)
            self.waited += 1
            try:
                await fut
            finally:
                if not fut.done():
                    fut.cancel()
        self.ops += 1
        self.bytes += nbytes
        self.peak_ops = max(self.peak_ops, self.ops)

    def release(self, nbytes: int = 0) -> None:
        self.ops = max(0, self.ops - 1)
        self.bytes = max(0, self.bytes - nbytes)
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    @property
    def saturated(self) -> bool:
        return self._would_block(0)

    def dump(self) -> dict:
        return {"ops": self.ops, "bytes": self.bytes,
                "max_ops": self.max_ops, "max_bytes": self.max_bytes,
                "peak_ops": self.peak_ops, "waited": self.waited,
                "queued_waiters": len(self._waiters)}
