"""librados analog: the public client API.

ref: src/librados/librados_cxx.cc (Rados / IoCtx) — connection
bootstrap via MonClient, pool handles, and synchronous+async object
ops riding the Objecter. Method names mirror the reference's C++ API
(``Rados::connect``, ``IoCtx::write/read/remove/stat``,
``IoCtx::get_omap_vals`` …) so reference users find what they expect.
"""

from __future__ import annotations

import json

import itertools

from ceph_tpu.mon.client import MonClient
from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg import Dispatcher, Keyring
from ceph_tpu.osd.messages import (
    MWatchNotify,
    OSD_OP_DELETE, OSD_OP_GETXATTR, OSD_OP_NOTIFY, OSD_OP_NOTIFY_ACK,
    OSD_OP_OMAP_GET, OSD_OP_OMAP_RM,
    OSD_OP_OMAP_SET, OSD_OP_PGLS, OSD_OP_READ, OSD_OP_SETXATTR,
    OSD_OP_SNAPTRIM, OSD_OP_STAT, OSD_OP_TRUNCATE, OSD_OP_UNWATCH,
    OSD_OP_WATCH, OSD_OP_WRITE, OSD_OP_WRITEFULL,
    OSD_OP_ZERO,
)
from ceph_tpu.osd.messages import OSD_FLAG_FULL_TRY
from ceph_tpu.osdc.objecter import Objecter, ObjectOperationError

__all__ = ["Rados", "IoCtx", "ObjectOperationError",
           "OSD_FLAG_FULL_TRY"]


class _WatchDispatcher(Dispatcher):
    """Delivers MWatchNotify to registered callbacks and auto-acks
    (ref: librados watch callback + notify_ack)."""

    def __init__(self, rados: "Rados"):
        self.rados = rados

    async def ms_dispatch(self, msg) -> bool:
        if not isinstance(msg, MWatchNotify):
            return False
        ent = self.rados._watches.get(msg.cookie)
        if ent is not None:
            ioctx, oid, cb = ent
            try:
                res = cb(msg.notify_id, msg.payload)
                if hasattr(res, "__await__"):
                    await res
            except Exception:
                pass
            # ack so the notifier's collection completes
            import asyncio
            asyncio.ensure_future(ioctx._op(oid, [
                (OSD_OP_NOTIFY_ACK, msg.notify_id, msg.cookie, "", b"")]))
        return True


class Rados:
    """ref: librados::Rados."""

    def __init__(self, monmap: MonMap, name: str = "client.admin",
                 keyring: Keyring | None = None,
                 config: dict | None = None):
        self.monc = MonClient(name, monmap, keyring=keyring)
        self.objecter = Objecter(self.monc, config=config)
        # cookie -> (ioctx, oid, callback)
        self._watches: dict[int, tuple] = {}
        self._cookie_gen = itertools.count(1)
        self.monc.msgr.add_dispatcher(_WatchDispatcher(self))

    async def connect(self) -> None:
        await self.monc.subscribe("osdmap", 0)
        # follow the monmap (round 6: membership changes at runtime)
        # and our own key lifecycle (rotation reaches us even with a
        # private keyring file)
        await self.monc.subscribe("monmap", 0)
        if self.monc.msgr.keyring is not None:
            await self.monc.subscribe("keyring", 0)
        await self.monc.wait_for_osdmap()

    async def shutdown(self) -> None:
        await self.monc.shutdown()

    async def mon_command(self, cmd, inbl: bytes = b"",
                          timeout: float = 30.0):
        return await self.monc.command(cmd, inbl, timeout=timeout)

    async def pool_create(self, name: str, pg_num: int = 32,
                          **kw) -> None:
        ret, rs, _ = await self.mon_command(
            dict({"prefix": "osd pool create", "pool": name,
                  "pg_num": pg_num}, **kw))
        if ret != 0:
            raise ObjectOperationError(ret, rs)

    async def pool_delete(self, name: str) -> None:
        ret, rs, _ = await self.mon_command(
            {"prefix": "osd pool rm", "pool": name})
        if ret != 0:
            raise ObjectOperationError(ret, rs)

    async def open_ioctx(self, pool_name: str) -> "IoCtx":
        pid = await self.objecter.pool_id(pool_name)
        return IoCtx(self, pid, pool_name)

    async def status(self) -> dict:
        ret, rs, out = await self.mon_command({"prefix": "status"})
        if ret != 0:
            raise ObjectOperationError(ret, rs)
        return json.loads(out)


class IoCtx:
    """ref: librados::IoCtx — per-pool I/O handle."""

    def __init__(self, rados: Rados, pool_id: int, pool_name: str):
        self.rados = rados
        self.pool_id = pool_id
        self.pool_name = pool_name
        # self-managed snap state (ref: IoCtx::selfmanaged_snap_set_
        # write_ctx / snap_set_read)
        self.snapc: tuple[int, list[int]] = (0, [])
        self.read_snap: int = 0

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        """Write snap context: seq = newest snap id, snaps = all live
        snap ids (newest first, like the reference)."""
        self.snapc = (seq, list(snaps))

    def snap_set_read(self, snap_id: int) -> None:
        """Subsequent reads serve the object state AT this snap
        (0 = head)."""
        self.read_snap = snap_id

    # ops that serve object STATE and therefore honor read_snap; any
    # other op (mutations, watch/unwatch/notify, notify-ack) must go to
    # the head regardless of snap_set_read — librados applies the read
    # snap to reads only (ref: IoCtx::snap_set_read)
    _SNAP_READ_OPS = frozenset((
        OSD_OP_READ, OSD_OP_STAT, OSD_OP_GETXATTR, OSD_OP_OMAP_GET))

    async def _op(self, oid: str, ops, timeout: float = 20.0,
                  snapc: tuple | None = None, snap_id: int | None = None,
                  flags: int = 0):
        if snapc is None:
            snapc = self.snapc if self.snapc[0] else None
        if snap_id is None:
            snap_id = self.read_snap if ops and all(
                o[0] in self._SNAP_READ_OPS for o in ops) else 0
        res, data, extra = await self.rados.objecter.op_submit(
            self.pool_id, oid, ops, timeout=timeout,
            snapc=snapc, snap_id=snap_id, flags=flags)
        if res < 0:
            raise ObjectOperationError(res, f"{oid}")
        return data, extra

    # -- self-managed snapshots -------------------------------------------
    async def selfmanaged_snap_create(self) -> int:
        """Allocate a new snap id from the pool (ref: librados
        selfmanaged_snap_create -> OSDMonitor pool snap_seq)."""
        ret, rs, out = await self.rados.mon_command(
            {"prefix": "osd pool selfmanaged-snap-create",
             "pool": self.pool_name})
        if ret != 0:
            raise ObjectOperationError(ret, rs)
        return json.loads(out)["snapid"]

    async def selfmanaged_snap_remove(self, snap_id: int) -> None:
        ret, rs, _ = await self.rados.mon_command(
            {"prefix": "osd pool selfmanaged-snap-remove",
             "pool": self.pool_name, "snapid": snap_id})
        if ret != 0:
            raise ObjectOperationError(ret, rs)

    async def snap_trim(self, oid: str, snap_id: int) -> None:
        """Drop one snap from one object's clones (the snap trimmer's
        unit of work, client-driven here)."""
        await self._op(oid, [(OSD_OP_SNAPTRIM, snap_id, 0, "", b"")])

    # -- watch/notify ------------------------------------------------------
    async def watch(self, oid: str, callback) -> int:
        """Register callback(notify_id, payload) for notifies on oid;
        returns the watch cookie (ref: IoCtx::watch2)."""
        cookie = next(self.rados._cookie_gen)
        self.rados._watches[cookie] = (self, oid, callback)
        try:
            await self._op(oid, [(OSD_OP_WATCH, cookie, 0, "", b"")])
        except BaseException:
            self.rados._watches.pop(cookie, None)   # no leak on failure
            raise
        return cookie

    async def unwatch(self, oid: str, cookie: int) -> None:
        self.rados._watches.pop(cookie, None)
        await self._op(oid, [(OSD_OP_UNWATCH, cookie, 0, "", b"")])

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout_ms: int = 2000) -> dict:
        """Send payload to every watcher, await their acks (ref:
        IoCtx::notify2). Returns {'acks': [...], 'timeouts': [...]}."""
        _, extra = await self._op(
            oid, [(OSD_OP_NOTIFY, timeout_ms, 0, "", bytes(payload))],
            timeout=max(20.0, timeout_ms / 1000 + 5))
        return extra

    # -- writes ------------------------------------------------------------
    # ``full_try`` (ref: librados OPERATION_FULL_TRY): a write blocked
    # by a FULL cluster / full pool fails fast with -ENOSPC/-EDQUOT
    # instead of parking until the condition clears.
    async def write(self, oid: str, data: bytes, offset: int = 0,
                    timeout: float = 20.0, snapc: tuple | None = None,
                    full_try: bool = False):
        await self._op(oid, [(OSD_OP_WRITE, offset, len(data), "",
                              bytes(data))], timeout=timeout, snapc=snapc,
                       flags=OSD_FLAG_FULL_TRY if full_try else 0)

    async def write_full(self, oid: str, data: bytes,
                         timeout: float = 20.0,
                         snapc: tuple | None = None,
                         full_try: bool = False):
        await self._op(oid, [(OSD_OP_WRITEFULL, 0, len(data), "",
                              bytes(data))], timeout=timeout, snapc=snapc,
                       flags=OSD_FLAG_FULL_TRY if full_try else 0)

    async def truncate(self, oid: str, size: int,
                       snapc: tuple | None = None):
        await self._op(oid, [(OSD_OP_TRUNCATE, size, 0, "", b"")],
                       snapc=snapc)

    async def zero(self, oid: str, offset: int, length: int,
                   snapc: tuple | None = None):
        await self._op(oid, [(OSD_OP_ZERO, offset, length, "", b"")],
                       snapc=snapc)

    async def remove(self, oid: str, snapc: tuple | None = None):
        await self._op(oid, [(OSD_OP_DELETE, 0, 0, "", b"")],
                       snapc=snapc)

    async def setxattr(self, oid: str, name: str, value: bytes):
        await self._op(oid, [(OSD_OP_SETXATTR, 0, 0, name,
                              bytes(value))])

    async def set_omap(self, oid: str, key: str, value: bytes):
        await self._op(oid, [(OSD_OP_OMAP_SET, 0, 0, key,
                              bytes(value))])

    async def rm_omap_key(self, oid: str, key: str):
        await self._op(oid, [(OSD_OP_OMAP_RM, 0, 0, key, b"")])

    # -- reads -------------------------------------------------------------
    async def read(self, oid: str, length: int = 0,
                   offset: int = 0, snap_id: int | None = None,
                   timeout: float = 20.0) -> bytes:
        data, _ = await self._op(
            oid, [(OSD_OP_READ, offset, length, "", b"")],
            snap_id=snap_id, timeout=timeout)
        return data

    async def stat(self, oid: str, snap_id: int | None = None) -> int:
        _, extra = await self._op(oid, [(OSD_OP_STAT, 0, 0, "", b"")],
                                  snap_id=snap_id)
        return extra["size"]

    async def getxattr(self, oid: str, name: str) -> bytes:
        data, _ = await self._op(
            oid, [(OSD_OP_GETXATTR, 0, 0, name, b"")])
        return data

    async def get_omap_vals(self, oid: str,
                            prefix: str = "") -> dict[str, bytes]:
        """All omap pairs, or only keys starting with ``prefix`` (the
        filter runs OSD-side — large omaps don't cross the wire;
        ref: the role of omap_get_vals' filter_prefix)."""
        _, extra = await self._op(
            oid, [(OSD_OP_OMAP_GET, 0, 0, prefix, b"")])
        return {k: bytes.fromhex(v)
                for k, v in extra.get("omap", {}).items()}

    async def list_objects(self) -> list[str]:
        """rados ls: union of per-PG listings (ref: librados
        nobjects_begin over pgls)."""
        osdmap = await self.rados.monc.wait_for_osdmap()
        pool = osdmap.pools[self.pool_id]
        names: set[str] = set()
        for seed in range(pool.pg_num):
            try:
                _, extra = await self._pg_op(
                    seed, [(OSD_OP_PGLS, 0, 0, "", b"")])
                names.update(extra.get("objects", []))
            except ObjectOperationError:
                continue
        return sorted(names)

    async def _pg_op(self, seed: int, ops):
        """Address a specific PG (pgls needs per-PG targeting) through
        the Objecter's full resend machinery."""
        res, data, extra = await self.rados.objecter.op_submit(
            self.pool_id, f".pgls.{seed}", ops, seed=seed, timeout=10.0)
        if res < 0:
            raise ObjectOperationError(res, f"pgls {seed}")
        return data, extra
