"""librados analog: the public client API.

ref: src/librados/librados_cxx.cc (Rados / IoCtx) — connection
bootstrap via MonClient, pool handles, and synchronous+async object
ops riding the Objecter. Method names mirror the reference's C++ API
(``Rados::connect``, ``IoCtx::write/read/remove/stat``,
``IoCtx::get_omap_vals`` …) so reference users find what they expect.
"""

from __future__ import annotations

import json

from ceph_tpu.mon.client import MonClient
from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg import Keyring
from ceph_tpu.osd.messages import (
    OSD_OP_DELETE, OSD_OP_GETXATTR, OSD_OP_OMAP_GET, OSD_OP_OMAP_RM,
    OSD_OP_OMAP_SET, OSD_OP_PGLS, OSD_OP_READ, OSD_OP_SETXATTR,
    OSD_OP_STAT, OSD_OP_TRUNCATE, OSD_OP_WRITE, OSD_OP_WRITEFULL,
    OSD_OP_ZERO,
)
from ceph_tpu.osdc.objecter import Objecter, ObjectOperationError

__all__ = ["Rados", "IoCtx", "ObjectOperationError"]


class Rados:
    """ref: librados::Rados."""

    def __init__(self, monmap: MonMap, name: str = "client.admin",
                 keyring: Keyring | None = None):
        self.monc = MonClient(name, monmap, keyring=keyring)
        self.objecter = Objecter(self.monc)

    async def connect(self) -> None:
        await self.monc.subscribe("osdmap", 0)
        await self.monc.wait_for_osdmap()

    async def shutdown(self) -> None:
        await self.monc.shutdown()

    async def mon_command(self, cmd, inbl: bytes = b"",
                          timeout: float = 30.0):
        return await self.monc.command(cmd, inbl, timeout=timeout)

    async def pool_create(self, name: str, pg_num: int = 32,
                          **kw) -> None:
        ret, rs, _ = await self.mon_command(
            dict({"prefix": "osd pool create", "pool": name,
                  "pg_num": pg_num}, **kw))
        if ret != 0:
            raise ObjectOperationError(ret, rs)

    async def pool_delete(self, name: str) -> None:
        ret, rs, _ = await self.mon_command(
            {"prefix": "osd pool rm", "pool": name})
        if ret != 0:
            raise ObjectOperationError(ret, rs)

    async def open_ioctx(self, pool_name: str) -> "IoCtx":
        pid = await self.objecter.pool_id(pool_name)
        return IoCtx(self, pid, pool_name)

    async def status(self) -> dict:
        ret, rs, out = await self.mon_command({"prefix": "status"})
        if ret != 0:
            raise ObjectOperationError(ret, rs)
        return json.loads(out)


class IoCtx:
    """ref: librados::IoCtx — per-pool I/O handle."""

    def __init__(self, rados: Rados, pool_id: int, pool_name: str):
        self.rados = rados
        self.pool_id = pool_id
        self.pool_name = pool_name

    async def _op(self, oid: str, ops, timeout: float = 20.0):
        res, data, extra = await self.rados.objecter.op_submit(
            self.pool_id, oid, ops, timeout=timeout)
        if res < 0:
            raise ObjectOperationError(res, f"{oid}")
        return data, extra

    # -- writes ------------------------------------------------------------
    async def write(self, oid: str, data: bytes, offset: int = 0):
        await self._op(oid, [(OSD_OP_WRITE, offset, len(data), "",
                              bytes(data))])

    async def write_full(self, oid: str, data: bytes):
        await self._op(oid, [(OSD_OP_WRITEFULL, 0, len(data), "",
                              bytes(data))])

    async def truncate(self, oid: str, size: int):
        await self._op(oid, [(OSD_OP_TRUNCATE, size, 0, "", b"")])

    async def zero(self, oid: str, offset: int, length: int):
        await self._op(oid, [(OSD_OP_ZERO, offset, length, "", b"")])

    async def remove(self, oid: str):
        await self._op(oid, [(OSD_OP_DELETE, 0, 0, "", b"")])

    async def setxattr(self, oid: str, name: str, value: bytes):
        await self._op(oid, [(OSD_OP_SETXATTR, 0, 0, name,
                              bytes(value))])

    async def set_omap(self, oid: str, key: str, value: bytes):
        await self._op(oid, [(OSD_OP_OMAP_SET, 0, 0, key,
                              bytes(value))])

    async def rm_omap_key(self, oid: str, key: str):
        await self._op(oid, [(OSD_OP_OMAP_RM, 0, 0, key, b"")])

    # -- reads -------------------------------------------------------------
    async def read(self, oid: str, length: int = 0,
                   offset: int = 0) -> bytes:
        data, _ = await self._op(
            oid, [(OSD_OP_READ, offset, length, "", b"")])
        return data

    async def stat(self, oid: str) -> int:
        _, extra = await self._op(oid, [(OSD_OP_STAT, 0, 0, "", b"")])
        return extra["size"]

    async def getxattr(self, oid: str, name: str) -> bytes:
        data, _ = await self._op(
            oid, [(OSD_OP_GETXATTR, 0, 0, name, b"")])
        return data

    async def get_omap_vals(self, oid: str) -> dict[str, bytes]:
        _, extra = await self._op(
            oid, [(OSD_OP_OMAP_GET, 0, 0, "", b"")])
        return {k: bytes.fromhex(v)
                for k, v in extra.get("omap", {}).items()}

    async def list_objects(self) -> list[str]:
        """rados ls: union of per-PG listings (ref: librados
        nobjects_begin over pgls)."""
        osdmap = await self.rados.monc.wait_for_osdmap()
        pool = osdmap.pools[self.pool_id]
        names: set[str] = set()
        for seed in range(pool.pg_num):
            try:
                _, extra = await self._pg_op(
                    seed, [(OSD_OP_PGLS, 0, 0, "", b"")])
                names.update(extra.get("objects", []))
            except ObjectOperationError:
                continue
        return sorted(names)

    async def _pg_op(self, seed: int, ops):
        """Address a specific PG (pgls needs per-PG targeting) through
        the Objecter's full resend machinery."""
        res, data, extra = await self.rados.objecter.op_submit(
            self.pool_id, f".pgls.{seed}", ops, seed=seed, timeout=10.0)
        if res < 0:
            raise ObjectOperationError(res, f"pgls {seed}")
        return data, extra
