"""AWS Signature Version 4 for the RGW gateway.

ref: the role of src/rgw/rgw_auth_s3.cc (AWSv4ComplMulti /
rgw_create_s3_v4_canonical_request) — request signing and verification
per the published SigV4 algorithm: canonical request -> string to sign
-> HMAC chain over (date, region, service, "aws4_request").

Header-based auth (``Authorization: AWS4-HMAC-SHA256 ...``) and
presigned query auth (``X-Amz-Signature=...`` — the shareable-URL
form, round 5) are implemented; chunked payload signing is not.
Payload integrity: the ``x-amz-content-sha256`` header is required on
header-signed requests and checked against the body unless it is
``UNSIGNED-PAYLOAD``; presigned requests are UNSIGNED-PAYLOAD by
definition and carry their own expiry (``X-Amz-Expires``).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
from urllib.parse import parse_qsl, quote

SERVICE = "s3"
UNSIGNED = "UNSIGNED-PAYLOAD"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def signing_key(secret: str, date: str, region: str) -> bytes:
    """The AWS4 key derivation chain (date is YYYYMMDD)."""
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, SERVICE)
    return _hmac(k, "aws4_request")


def canonical_query(query: str) -> str:
    pairs = parse_qsl(query, keep_blank_values=True)
    enc = sorted((quote(k, safe="-_.~"), quote(v, safe="-_.~"))
                 for k, v in pairs)
    return "&".join(f"{k}={v}" for k, v in enc)


def canonical_request(method: str, path: str, query: str,
                      headers: dict[str, str],
                      signed_headers: list[str],
                      payload_hash: str) -> str:
    ch = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([
        method.upper(),
        quote(path, safe="/-_.~"),
        canonical_query(query),
        ch,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(amzdate: str, scope: str, creq: str) -> str:
    return "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                      _sha256(creq.encode())])


def sign(method: str, path: str, query: str, headers: dict[str, str],
         payload: bytes, access: str, secret: str,
         region: str = "us-east-1",
         amzdate: str | None = None) -> dict[str, str]:
    """Client side: returns the headers to add (x-amz-date,
    x-amz-content-sha256, authorization). ``headers`` must already
    contain everything to be signed (at least ``host``)."""
    if amzdate is None:
        amzdate = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
    date = amzdate[:8]
    payload_hash = _sha256(payload)
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = amzdate
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = sorted(hdrs)
    creq = canonical_request(method, path, query, hdrs, signed,
                             payload_hash)
    scope = f"{date}/{region}/{SERVICE}/aws4_request"
    sig = hmac.new(signing_key(secret, date, region),
                   string_to_sign(amzdate, scope, creq).encode(),
                   hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return {"x-amz-date": amzdate, "x-amz-content-sha256": payload_hash,
            "authorization": auth}


def verify(method: str, path: str, query: str, headers: dict[str, str],
           payload: bytes, secrets: dict[str, str],
           max_skew_s: float = 900.0) -> tuple[bool, str]:
    """Server side: (ok, reason). ``headers`` keys must be lower-case
    (the gateway's parser lower-cases them). Requests whose
    ``x-amz-date`` is more than ``max_skew_s`` from now are rejected —
    the replay window (ref: rgw's RGW_AUTH_GRACE clock-skew check)."""
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        return False, "missing or non-SigV4 Authorization"
    fields = {}
    for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
        k, _, v = part.strip().partition("=")
        fields[k] = v
    try:
        cred = fields["Credential"].split("/")
        access, date, region, service, terminal = cred
        signed = fields["SignedHeaders"].split(";")
        given = fields["Signature"]
    except (KeyError, ValueError):
        return False, "malformed Authorization"
    if service != SERVICE or terminal != "aws4_request":
        return False, "bad credential scope"
    secret = secrets.get(access)
    if secret is None:
        return False, "unknown access key"
    amzdate = headers.get("x-amz-date", "")
    if amzdate[:8] != date:
        return False, "x-amz-date does not match credential date"
    try:
        when = datetime.datetime.strptime(
            amzdate, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
    except ValueError:
        return False, "malformed x-amz-date"
    now = datetime.datetime.now(datetime.timezone.utc)
    if abs((now - when).total_seconds()) > max_skew_s:
        return False, "request time outside the replay window"
    payload_hash = headers.get("x-amz-content-sha256", "")
    if not payload_hash:
        return False, "missing x-amz-content-sha256"
    if payload_hash != UNSIGNED and payload_hash != _sha256(payload):
        return False, "payload hash mismatch"
    creq = canonical_request(method, path, query, headers, signed,
                             payload_hash)
    scope = f"{date}/{region}/{SERVICE}/aws4_request"
    want = hmac.new(signing_key(secret, date, region),
                    string_to_sign(amzdate, scope, creq).encode(),
                    hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, given):
        return False, "signature mismatch"
    return True, access


def presign(method: str, path: str, host: str, access: str,
            secret: str, expires: int = 3600,
            region: str = "us-east-1", query: str = "",
            amzdate: str | None = None) -> str:
    """Client side: the full query string of a presigned URL (ref: the
    GET-object sharing flow rgw serves for radosgw-admin-issued keys).
    Signs method+path+query with the payload pinned UNSIGNED-PAYLOAD
    and only ``host`` in SignedHeaders, per the SigV4 query spec."""
    if amzdate is None:
        amzdate = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
    date = amzdate[:8]
    scope = f"{date}/{region}/{SERVICE}/aws4_request"
    q = list(parse_qsl(query, keep_blank_values=True))
    q += [("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
          ("X-Amz-Credential", f"{access}/{scope}"),
          ("X-Amz-Date", amzdate),
          ("X-Amz-Expires", str(int(expires))),
          ("X-Amz-SignedHeaders", "host")]
    qs = "&".join(f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}"
                  for k, v in q)
    creq = canonical_request(method, path, qs, {"host": host},
                             ["host"], UNSIGNED)
    sig = hmac.new(signing_key(secret, date, region),
                   string_to_sign(amzdate, scope, creq).encode(),
                   hashlib.sha256).hexdigest()
    return qs + f"&X-Amz-Signature={sig}"


def verify_presigned(method: str, path: str, query: str,
                     headers: dict[str, str],
                     secrets: dict[str, str]) -> tuple[bool, str]:
    """Server side for X-Amz-Signature query auth: (ok, access|reason).

    The canonical request re-signs every query pair EXCEPT
    X-Amz-Signature itself; expiry comes from X-Amz-Date +
    X-Amz-Expires rather than the fixed clock-skew window."""
    pairs = parse_qsl(query, keep_blank_values=True)
    params = dict(pairs)
    given = params.get("X-Amz-Signature")
    if not given:
        return False, "missing X-Amz-Signature"
    if params.get("X-Amz-Algorithm") != "AWS4-HMAC-SHA256":
        return False, "unsupported X-Amz-Algorithm"
    try:
        access, date, region, service, terminal = \
            params["X-Amz-Credential"].split("/")
        amzdate = params["X-Amz-Date"]
        expires = int(params["X-Amz-Expires"])
        signed = params["X-Amz-SignedHeaders"].split(";")
    except (KeyError, ValueError):
        return False, "malformed presigned parameters"
    if service != SERVICE or terminal != "aws4_request":
        return False, "bad credential scope"
    # SigV4 query-auth bounds: expiry must be positive and at most 7
    # days (ref: rgw's X-Amz-Expires validation) — otherwise a key
    # holder can mint effectively never-expiring URLs
    if expires <= 0 or expires > 604800:
        return False, "X-Amz-Expires out of range (0, 604800]"
    # a presigned signature not bound to the host header could be
    # replayed against another endpoint sharing the key
    if "host" not in signed:
        return False, "SignedHeaders must include host"
    if amzdate[:8] != date:
        return False, "X-Amz-Date does not match credential date"
    secret = secrets.get(access)
    if secret is None:
        return False, "unknown access key"
    try:
        when = datetime.datetime.strptime(
            amzdate, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
    except ValueError:
        return False, "malformed X-Amz-Date"
    now = datetime.datetime.now(datetime.timezone.utc)
    age = (now - when).total_seconds()
    if age > expires or age < -900:
        return False, "presigned URL expired"
    qs = "&".join(f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}"
                  for k, v in pairs if k != "X-Amz-Signature")
    creq = canonical_request(method, path, qs, headers, signed,
                             UNSIGNED)
    scope = f"{date}/{region}/{SERVICE}/aws4_request"
    want = hmac.new(signing_key(secret, date, region),
                    string_to_sign(amzdate, scope, creq).encode(),
                    hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, given):
        return False, "signature mismatch"
    return True, access
