"""RGW-lite: an S3-subset HTTP object gateway over RADOS.

ref: src/rgw/ (RGWRados + the beast frontend + RGWOp hierarchy) —
rebuilt small: an asyncio HTTP frontend translating the core S3
operations onto one backing pool. Buckets are omap *bucket index*
objects (ref: RGW bucket index shards); object payloads live in
``<bucket>/<key>`` RADOS objects. XML response shapes follow S3's
ListAllMyBucketsResult / ListBucketResult so s3-style clients parse
them.

Supported: PUT/DELETE bucket, GET / (list buckets), PUT/GET/HEAD/
DELETE object, GET bucket (list objects). Not built: multipart,
ACLs/auth signatures, versioning, multisite replication.
"""

from __future__ import annotations

import asyncio
from urllib.parse import unquote
from xml.sax.saxutils import escape

from ceph_tpu.rados import IoCtx, ObjectOperationError
from ceph_tpu.utils.logging import get_logger

log = get_logger("rgw")

BUCKETS_ROOT = ".rgw.buckets"          # omap: bucket name -> b"1"


def _index(bucket: str) -> str:
    return f".bucket.{bucket}"


def _obj(bucket: str, key: str) -> str:
    return f"{bucket}/{key}"


class RGWGateway:
    """ref: RGWHTTPFrontend + RGWOp dispatch."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> int:
        self._server = await asyncio.start_server(self._serve, host,
                                                  port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.dout(1, f"rgw listening on :{self.port}")
        return self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()

    # -- http plumbing -----------------------------------------------------
    async def _serve(self, reader, writer) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), timeout=10)
            if not req:
                return
            method, path, _ = req.decode().split(" ", 2)
            headers: dict[str, str] = {}
            while True:
                line = await asyncio.wait_for(reader.readline(),
                                              timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", 0))
            if n:
                body = await asyncio.wait_for(reader.readexactly(n),
                                              timeout=30)
            status, ctype, payload = await self._dispatch(
                method.upper(), unquote(path.split("?", 1)[0]), body)
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n".encode() + payload)
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError, OSError, ValueError) as e:
            log.dout(5, f"rgw client error: {e}")
        finally:
            writer.close()

    # -- op dispatch (ref: RGWOp subclasses) --------------------------------
    async def _dispatch(self, method: str, path: str,
                        body: bytes) -> tuple[str, str, bytes]:
        parts = [p for p in path.split("/") if p]
        try:
            if not parts:
                if method == "GET":
                    return await self._list_buckets()
                return "405 Method Not Allowed", "text/plain", b""
            bucket = parts[0]
            key = "/".join(parts[1:])
            if not key:
                if method == "PUT":
                    return await self._create_bucket(bucket)
                if method == "DELETE":
                    return await self._delete_bucket(bucket)
                if method == "GET":
                    return await self._list_objects(bucket)
                return "405 Method Not Allowed", "text/plain", b""
            if method == "PUT":
                return await self._put_object(bucket, key, body)
            if method == "GET":
                return await self._get_object(bucket, key)
            if method == "HEAD":
                return await self._get_object(bucket, key, head=True)
            if method == "DELETE":
                return await self._delete_object(bucket, key)
            return "405 Method Not Allowed", "text/plain", b""
        except ObjectOperationError as e:
            if e.errno == -2:
                return "404 Not Found", "application/xml", \
                    b"<Error><Code>NoSuchKey</Code></Error>"
            return "500 Internal Server Error", "text/plain", \
                str(e).encode()

    async def _bucket_exists(self, bucket: str) -> bool:
        try:
            omap = await self.ioctx.get_omap_vals(BUCKETS_ROOT)
        except ObjectOperationError:
            return False
        return bucket in omap

    async def _list_buckets(self):
        try:
            omap = await self.ioctx.get_omap_vals(BUCKETS_ROOT)
        except ObjectOperationError:
            omap = {}
        items = "".join(
            f"<Bucket><Name>{escape(b)}</Name></Bucket>"
            for b in sorted(omap))
        xml = (f'<?xml version="1.0"?><ListAllMyBucketsResult>'
               f"<Buckets>{items}</Buckets>"
               f"</ListAllMyBucketsResult>")
        return "200 OK", "application/xml", xml.encode()

    async def _create_bucket(self, bucket: str):
        await self.ioctx.set_omap(BUCKETS_ROOT, bucket, b"1")
        await self.ioctx.set_omap(_index(bucket), "_created", b"1")
        return "200 OK", "application/xml", b""

    async def _delete_bucket(self, bucket: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>"
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        if any(k.startswith("k:") for k in idx):
            return "409 Conflict", "application/xml", \
                b"<Error><Code>BucketNotEmpty</Code></Error>"
        await self.ioctx.remove(_index(bucket))
        await self.ioctx.rm_omap_key(BUCKETS_ROOT, bucket)
        return "204 No Content", "application/xml", b""

    async def _list_objects(self, bucket: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>"
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        items = "".join(
            f"<Contents><Key>{escape(k[2:])}</Key>"
            f"<Size>{int.from_bytes(v, 'little')}</Size></Contents>"
            for k, v in sorted(idx.items())
            if k.startswith("k:"))
        xml = (f'<?xml version="1.0"?><ListBucketResult>'
               f"<Name>{escape(bucket)}</Name>{items}"
               f"</ListBucketResult>")
        return "200 OK", "application/xml", xml.encode()

    async def _put_object(self, bucket: str, key: str, body: bytes):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>"
        await self.ioctx.write_full(_obj(bucket, key), body)
        # "k:" prefix keeps user keys out of the index meta namespace
        await self.ioctx.set_omap(_index(bucket), f"k:{key}",
                                  len(body).to_bytes(8, "little"))
        return "200 OK", "application/xml", b""

    async def _get_object(self, bucket: str, key: str,
                          head: bool = False):
        data = await self.ioctx.read(_obj(bucket, key))
        return "200 OK", "application/octet-stream", \
            b"" if head else data

    async def _delete_object(self, bucket: str, key: str):
        await self.ioctx.remove(_obj(bucket, key))
        try:
            await self.ioctx.rm_omap_key(_index(bucket), f"k:{key}")
        except ObjectOperationError:
            pass
        return "204 No Content", "application/xml", b""
