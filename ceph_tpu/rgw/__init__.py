"""RGW-lite: an S3-subset HTTP object gateway over RADOS.

ref: src/rgw/ (RGWRados + the beast frontend + RGWOp hierarchy) —
rebuilt small: an asyncio HTTP frontend translating the core S3
operations onto one backing pool. Buckets are omap *bucket index*
objects (ref: RGW bucket index shards); object payloads live in
``<bucket>/<key>`` RADOS objects. XML response shapes follow S3's
ListAllMyBucketsResult / ListBucketResult so s3-style clients parse
them.

Multipart uploads (round 4) follow RGW's manifest design: parts stay
as their own RADOS objects and Complete writes a *manifest* into the
bucket index (ref: RGWObjManifest) — GET streams the parts in order;
nothing is ever re-concatenated at rest. The multipart ETag is the S3
convention md5(concat(binary part md5s))-N.

Auth (round 4): AWS Signature V4 header auth (``rgw/auth.py``).
Construct the gateway with ``users={access: secret}`` to require a
valid signature on every request (403 AccessDenied otherwise); omit
it for anonymous mode.

Round 5: presigned URLs (SigV4 query auth — ``auth.presign`` issues,
the gateway verifies and expires them) and canned ACLs (``private`` /
``public-read`` at bucket and object level via ``x-amz-acl`` and the
``?acl`` sub-resource; writes are owner-only, public-read admits
anonymous GETs — ref: RGWAccessControlPolicy reduced to the two
grants that matter).

Supported: PUT/DELETE bucket, GET / (list buckets), PUT/GET/HEAD/
DELETE object, GET bucket (list objects), multipart
initiate/upload-part/list-parts/list-uploads/complete/abort, SigV4
header + presigned query auth, canned ACLs.
Not built: versioning, multisite replication, full grantee lists.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import re
import uuid
from urllib.parse import parse_qsl, unquote
from xml.sax.saxutils import escape

from ceph_tpu.rados import IoCtx, ObjectOperationError
from ceph_tpu.rgw import auth as sigv4
from ceph_tpu.utils.locks import KeyedLocks
from ceph_tpu.utils.logging import get_logger

log = get_logger("rgw")

BUCKETS_ROOT = ".rgw.buckets"          # omap: bucket name -> b"1"


def _index(bucket: str) -> str:
    return f".bucket.{bucket}"


def _obj(bucket: str, key: str) -> str:
    return f"{bucket}/{key}"


def _part_obj(bucket: str, upload_id: str, n: int) -> str:
    return f".mp.{bucket}.{upload_id}.{n}"


class RGWGateway:
    """ref: RGWHTTPFrontend + RGWOp dispatch."""

    def __init__(self, ioctx: IoCtx,
                 users: dict[str, str] | None = None):
        self.ioctx = ioctx
        self.users = users or {}
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None
        # Serialize mutations of one (bucket, key): object PUT/DELETE,
        # part uploads, and multipart complete/abort are
        # read-modify-write sequences over the bucket-index manifest
        # row — racing them can leave a manifest referencing part
        # objects the other path just removed (GET then 500s) or
        # orphan parts. Single-process gateway, so in-memory locks
        # suffice (the reference shards this through the bucket-index
        # OSD class ops).
        self._key_locks = KeyedLocks()

    def _key_lock(self, bucket: str, key: str):
        return self._key_locks.hold((bucket, key))

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> int:
        self._server = await asyncio.start_server(self._serve, host,
                                                  port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.dout(1, f"rgw listening on :{self.port}")
        return self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()

    # -- http plumbing -----------------------------------------------------
    async def _serve(self, reader, writer) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), timeout=10)
            if not req:
                return
            method, target, _ = req.decode().split(" ", 2)
            headers: dict[str, str] = {}
            while True:
                line = await asyncio.wait_for(reader.readline(),
                                              timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", 0))
            if n:
                body = await asyncio.wait_for(reader.readexactly(n),
                                              timeout=30)
            raw_path, _, query = target.partition("?")
            status, ctype, payload, extra = await self._dispatch(
                method.upper(), unquote(raw_path), query, headers, body)
            # HEAD handlers advertise the real object size via an
            # explicit Content-Length override (body stays empty)
            clen = extra.pop("Content-Length", len(payload))
            hdr_lines = "".join(f"{k}: {v}\r\n" for k, v in extra.items())
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {clen}\r\n{hdr_lines}"
                f"Connection: close\r\n\r\n".encode() + payload)
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError, OSError, ValueError) as e:
            log.dout(5, f"rgw client error: {e}")
        finally:
            writer.close()

    # -- authn/authz (ref: RGWHandler_REST auth + RGWAccessControlPolicy) --
    _DENIED = ("403 Forbidden", "application/xml",
               b"<Error><Code>AccessDenied</Code></Error>", {})

    async def _bucket_meta(self, bucket: str) -> dict | None:
        """Owner + canned ACL of a bucket ({'owner':..., 'acl':...}),
        or None when the bucket does not exist. Legacy b'1' rows read
        as ownerless/private (any authenticated principal passes)."""
        try:
            rows = await self.ioctx.get_omap_vals(BUCKETS_ROOT,
                                                  prefix=bucket)
        except ObjectOperationError:
            return None                  # no bucket root object yet
        raw = rows.get(bucket)
        if raw is None:
            return None
        if raw == b"1":
            return {"owner": "", "acl": "private"}
        return json.loads(raw)

    async def _authz(self, ident: str | None, bucket: str, key: str,
                     write: bool, meta: dict | None) -> bool:
        """Canned-ACL policy check (only when auth is configured):
        writes are owner-only; reads pass for the owner or when the
        bucket (or, for objects, the object) is public-read — which
        also admits anonymous principals, the presigned-URL
        complement. ``meta`` is the bucket meta the dispatcher already
        resolved (one read per request, shared with the acl
        handlers)."""
        if meta is None:
            return True                  # let handlers return NoSuchBucket
        owner = meta.get("owner", "")
        if ident is not None and (not owner or ident == owner):
            return True
        if write:
            return False
        if meta.get("acl") == "public-read":
            return True
        if key:
            try:
                oacl = await self.ioctx.get_omap_vals(
                    _index(bucket), prefix=f"a:{key}")
            except ObjectOperationError:
                return False
            if oacl.get(f"a:{key}") == b"public-read":
                return True
        return False

    # -- op dispatch (ref: RGWOp subclasses) --------------------------------
    async def _dispatch(self, method: str, path: str, query: str,
                        headers: dict[str, str],
                        body: bytes) -> tuple[str, str, bytes, dict]:
        q = dict(parse_qsl(query, keep_blank_values=True))
        ident: str | None = None
        if self.users:
            if "X-Amz-Signature" in q:
                ok, who = sigv4.verify_presigned(method, path, query,
                                                 headers, self.users)
            elif "authorization" in headers:
                ok, who = sigv4.verify(method, path, query, headers,
                                       body, self.users)
            else:
                ok, who = True, None     # anonymous: ACLs gate below
            if not ok:
                log.dout(5, f"sigv4 reject: {who}")
                return self._DENIED
            ident = who
        parts = [p for p in path.split("/") if p]
        try:
            if not parts:
                if method == "GET":
                    if self.users and ident is None:
                        return self._DENIED  # service op: no anonymous
                    return await self._list_buckets()   # bucket survey
                return "405 Method Not Allowed", "text/plain", b"", {}
            bucket = parts[0]
            key = "/".join(parts[1:])
            meta = None
            if self.users or "acl" in q:
                meta = await self._bucket_meta(bucket)
            if self.users:
                write = method not in ("GET", "HEAD")
                if not await self._authz(ident, bucket, key, write,
                                         meta):
                    return self._DENIED
            if not key:
                # ?acl sub-resource FIRST: a plain-PUT match would
                # turn PUT /bucket?acl into bucket creation
                if method == "GET" and "acl" in q:
                    return await self._get_acl(bucket, "", meta)
                if method == "PUT" and "acl" in q:
                    return await self._put_acl(
                        bucket, "", headers.get("x-amz-acl", "private"),
                        meta)
                if method == "PUT":
                    return await self._create_bucket(
                        bucket, ident,
                        headers.get("x-amz-acl", "private"))
                if method == "DELETE":
                    return await self._delete_bucket(bucket)
                if method == "GET" and "uploads" in q:
                    return await self._list_uploads(bucket)
                if method == "GET":
                    return await self._list_objects(bucket)
                return "405 Method Not Allowed", "text/plain", b"", {}
            if method == "GET" and "acl" in q:
                return await self._get_acl(bucket, key, meta)
            if method == "PUT" and "acl" in q:
                return await self._put_acl(
                    bucket, key, headers.get("x-amz-acl", "private"),
                    meta)
            if method == "POST" and "uploads" in q:
                return await self._initiate_multipart(bucket, key)
            if method == "POST" and "uploadId" in q:
                async with self._key_lock(bucket, key):
                    return await self._complete_multipart(
                        bucket, key, q["uploadId"], body)
            if method == "PUT" and "uploadId" in q:
                pn = q.get("partNumber", "")
                if not pn.isdigit():
                    return ("400 Bad Request", "application/xml",
                            b"<Error><Code>InvalidPartNumber</Code>"
                            b"</Error>", {})
                # under the key lock: a part landing after a racing
                # abort removed the upload meta would re-create the
                # part object + index row with nothing left to ever
                # clean them up
                async with self._key_lock(bucket, key):
                    return await self._put_part(
                        bucket, key, q["uploadId"], int(pn), body)
            if method == "DELETE" and "uploadId" in q:
                async with self._key_lock(bucket, key):
                    return await self._abort_multipart(bucket, key,
                                                       q["uploadId"])
            if method == "GET" and "uploadId" in q:
                return await self._list_parts(bucket, key,
                                              q["uploadId"])
            if method == "PUT":
                async with self._key_lock(bucket, key):
                    return await self._put_object(
                        bucket, key, body,
                        acl=headers.get("x-amz-acl"))
            if method == "GET":
                return await self._get_object(bucket, key)
            if method == "HEAD":
                return await self._get_object(bucket, key, head=True)
            if method == "DELETE":
                async with self._key_lock(bucket, key):
                    return await self._delete_object(bucket, key)
            return "405 Method Not Allowed", "text/plain", b"", {}
        except ObjectOperationError as e:
            if e.errno == -2:
                return "404 Not Found", "application/xml", \
                    b"<Error><Code>NoSuchKey</Code></Error>", {}
            return "500 Internal Server Error", "text/plain", \
                str(e).encode(), {}

    async def _bucket_exists(self, bucket: str) -> bool:
        try:
            omap = await self.ioctx.get_omap_vals(BUCKETS_ROOT)
        except ObjectOperationError:
            return False
        return bucket in omap

    async def _list_buckets(self):
        try:
            omap = await self.ioctx.get_omap_vals(BUCKETS_ROOT)
        except ObjectOperationError:
            omap = {}
        items = "".join(
            f"<Bucket><Name>{escape(b)}</Name></Bucket>"
            for b in sorted(omap))
        xml = (f'<?xml version="1.0"?><ListAllMyBucketsResult>'
               f"<Buckets>{items}</Buckets>"
               f"</ListAllMyBucketsResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _create_bucket(self, bucket: str, owner: str | None = None,
                             acl: str = "private"):
        if self.users and owner is None:
            return self._DENIED          # anonymous cannot own a bucket
        if acl not in ("private", "public-read"):
            acl = "private"
        meta = json.dumps({"owner": owner or "", "acl": acl}).encode()
        await self.ioctx.set_omap(BUCKETS_ROOT, bucket, meta)
        await self.ioctx.set_omap(_index(bucket), "_created", b"1")
        return "200 OK", "application/xml", b"", {}

    async def _key_exists(self, bucket: str, key: str) -> bool:
        try:
            rows = await self.ioctx.get_omap_vals(_index(bucket),
                                                  prefix=f"k:{key}")
        except ObjectOperationError:
            return False
        return f"k:{key}" in rows

    async def _get_acl(self, bucket: str, key: str,
                       meta: dict | None = None):
        if meta is None:
            meta = await self._bucket_meta(bucket)
        if meta is None:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        acl = meta.get("acl", "private")
        if key:
            if not await self._key_exists(bucket, key):
                return "404 Not Found", "application/xml", \
                    b"<Error><Code>NoSuchKey</Code></Error>", {}
            rows = await self.ioctx.get_omap_vals(_index(bucket),
                                                  prefix=f"a:{key}")
            oacl = rows.get(f"a:{key}")
            if oacl is not None:
                acl = oacl.decode()
        grants = ('<Grant><Grantee>owner</Grantee>'
                  '<Permission>FULL_CONTROL</Permission></Grant>')
        if acl == "public-read":
            grants += ('<Grant><Grantee>AllUsers</Grantee>'
                       '<Permission>READ</Permission></Grant>')
        xml = (f'<?xml version="1.0"?><AccessControlPolicy>'
               f"<Owner><ID>{escape(meta.get('owner', ''))}</ID></Owner>"
               f"<AccessControlList>{grants}</AccessControlList>"
               f"</AccessControlPolicy>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _put_acl(self, bucket: str, key: str, acl: str,
                       meta: dict | None = None):
        if meta is None:
            meta = await self._bucket_meta(bucket)
        if meta is None:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        if acl not in ("private", "public-read"):
            return ("400 Bad Request", "application/xml",
                    b"<Error><Code>InvalidArgument</Code></Error>", {})
        if key:
            if not await self._key_exists(bucket, key):
                return "404 Not Found", "application/xml", \
                    b"<Error><Code>NoSuchKey</Code></Error>", {}
            await self.ioctx.set_omap(_index(bucket), f"a:{key}",
                                      acl.encode())
        else:
            meta["acl"] = acl
            await self.ioctx.set_omap(BUCKETS_ROOT, bucket,
                                      json.dumps(meta).encode())
        return "200 OK", "application/xml", b"", {}

    async def _delete_bucket(self, bucket: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        if any(k.startswith("k:") for k in idx):
            return "409 Conflict", "application/xml", \
                b"<Error><Code>BucketNotEmpty</Code></Error>", {}
        await self.ioctx.remove(_index(bucket))
        await self.ioctx.rm_omap_key(BUCKETS_ROOT, bucket)
        return "204 No Content", "application/xml", b"", {}

    async def _list_objects(self, bucket: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        items = "".join(
            f"<Contents><Key>{escape(k[2:])}</Key>"
            f"<Size>{int.from_bytes(v, 'little')}</Size></Contents>"
            for k, v in sorted(idx.items())
            if k.startswith("k:"))
        xml = (f'<?xml version="1.0"?><ListBucketResult>'
               f"<Name>{escape(bucket)}</Name>{items}"
               f"</ListBucketResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _put_object(self, bucket: str, key: str, body: bytes,
                          acl: str | None = None):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        await self._drop_manifest(bucket, key)
        await self.ioctx.write_full(_obj(bucket, key), body)
        # "k:" prefix keeps user keys out of the index meta namespace
        await self.ioctx.set_omap(_index(bucket), f"k:{key}",
                                  len(body).to_bytes(8, "little"))
        if acl in ("private", "public-read"):
            await self.ioctx.set_omap(_index(bucket), f"a:{key}",
                                      acl.encode())
        else:                    # overwrite clears any stale object acl
            try:
                await self.ioctx.rm_omap_key(_index(bucket), f"a:{key}")
            except ObjectOperationError:
                pass
        etag = hashlib.md5(body).hexdigest()
        return "200 OK", "application/xml", b"", {"ETag": f'"{etag}"'}

    async def _manifest(self, bucket: str, key: str,
                        idx: dict | None = None) -> dict | None:
        if idx is None:
            # prefix fetch: the OSD ships only this key's manifest row,
            # not the whole bucket index (hot GET path)
            idx = await self.ioctx.get_omap_vals(_index(bucket),
                                                 prefix=f"m:{key}")
        raw = idx.get(f"m:{key}")
        return json.loads(raw) if raw else None

    async def _drop_manifest(self, bucket: str, key: str,
                             idx: dict | None = None) -> None:
        """Overwriting / deleting a multipart object must free its
        part objects (ref: RGWRados gc on manifest replace)."""
        try:
            man = await self._manifest(bucket, key, idx)
        except ObjectOperationError:
            return
        if not man:
            return
        for part_oid, _ in man["parts"]:
            try:
                await self.ioctx.remove(part_oid)
            except ObjectOperationError:
                pass
        await self.ioctx.rm_omap_key(_index(bucket), f"m:{key}")

    async def _get_object(self, bucket: str, key: str,
                          head: bool = False):
        try:
            man = await self._manifest(bucket, key)
        except ObjectOperationError:
            man = None
        if man:
            if head:
                total = sum(s for _, s in man["parts"])
                return ("200 OK", "application/octet-stream", b"",
                        {"ETag": f'"{man["etag"]}"',
                         "Content-Length": total})
            chunks = [await self.ioctx.read(oid)
                      for oid, _ in man["parts"]]
            return ("200 OK", "application/octet-stream",
                    b"".join(chunks), {"ETag": f'"{man["etag"]}"'})
        if head:     # stat, don't transfer (HEAD of a large object)
            size = await self.ioctx.stat(_obj(bucket, key))
            return ("200 OK", "application/octet-stream", b"",
                    {"Content-Length": size})
        data = await self.ioctx.read(_obj(bucket, key))
        return "200 OK", "application/octet-stream", data, {}

    async def _delete_object(self, bucket: str, key: str):
        await self._drop_manifest(bucket, key)
        try:
            await self.ioctx.remove(_obj(bucket, key))
        except ObjectOperationError:
            pass
        for row in (f"k:{key}", f"a:{key}"):
            try:
                await self.ioctx.rm_omap_key(_index(bucket), row)
            except ObjectOperationError:
                pass
        return "204 No Content", "application/xml", b"", {}

    # -- multipart (ref: RGWPutObjProcessor_Multipart + RGWObjManifest) ----
    async def _initiate_multipart(self, bucket: str, key: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        upload_id = uuid.uuid4().hex
        await self.ioctx.set_omap(
            _index(bucket), f"u:{upload_id}",
            json.dumps({"key": key}).encode())
        xml = (f'<?xml version="1.0"?><InitiateMultipartUploadResult>'
               f"<Bucket>{escape(bucket)}</Bucket>"
               f"<Key>{escape(key)}</Key>"
               f"<UploadId>{upload_id}</UploadId>"
               f"</InitiateMultipartUploadResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _upload_meta(self, bucket: str, upload_id: str):
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        raw = idx.get(f"u:{upload_id}")
        return (json.loads(raw) if raw else None), idx

    async def _put_part(self, bucket: str, key: str, upload_id: str,
                        part_num: int, body: bytes):
        meta, _ = await self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchUpload</Code></Error>", {}
        if part_num < 1 or part_num > 10000:
            return "400 Bad Request", "application/xml", \
                b"<Error><Code>InvalidPartNumber</Code></Error>", {}
        etag = hashlib.md5(body).hexdigest()
        await self.ioctx.write_full(_part_obj(bucket, upload_id,
                                              part_num), body)
        await self.ioctx.set_omap(
            _index(bucket), f"up:{upload_id}:{part_num:05d}",
            json.dumps({"etag": etag, "size": len(body)}).encode())
        return "200 OK", "application/xml", b"", {"ETag": f'"{etag}"'}

    async def _list_parts(self, bucket: str, key: str, upload_id: str):
        meta, idx = await self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchUpload</Code></Error>", {}
        pfx = f"up:{upload_id}:"
        rows = []
        for k, v in sorted(idx.items()):
            if k.startswith(pfx):
                info = json.loads(v)
                rows.append(
                    f"<Part><PartNumber>{int(k[len(pfx):])}</PartNumber>"
                    f'<ETag>"{info["etag"]}"</ETag>'
                    f"<Size>{info['size']}</Size></Part>")
        xml = (f'<?xml version="1.0"?><ListPartsResult>'
               f"<Bucket>{escape(bucket)}</Bucket>"
               f"<Key>{escape(key)}</Key>"
               f"<UploadId>{upload_id}</UploadId>{''.join(rows)}"
               f"</ListPartsResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _list_uploads(self, bucket: str):
        if not await self._bucket_exists(bucket):
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchBucket</Code></Error>", {}
        idx = await self.ioctx.get_omap_vals(_index(bucket))
        rows = []
        for k, v in sorted(idx.items()):
            if k.startswith("u:"):
                meta = json.loads(v)
                rows.append(
                    f"<Upload><Key>{escape(meta['key'])}</Key>"
                    f"<UploadId>{k[2:]}</UploadId></Upload>")
        xml = (f'<?xml version="1.0"?><ListMultipartUploadsResult>'
               f"<Bucket>{escape(bucket)}</Bucket>{''.join(rows)}"
               f"</ListMultipartUploadsResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _complete_multipart(self, bucket: str, key: str,
                                  upload_id: str, body: bytes):
        meta, idx = await self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchUpload</Code></Error>", {}
        # The client's CompleteMultipartUpload body lists the parts it
        # wants in order with their ETags; parse <Part> blocks loosely
        # like rgw does for dialect tolerance, falling back to "all
        # uploaded parts" when the body names none.
        text = body.decode(errors="replace")
        listed = []
        for blk in re.findall(r"<Part>(.*?)</Part>", text, re.S):
            pn = re.search(r"<PartNumber>\s*(\d+)\s*</PartNumber>", blk)
            et = re.search(r"<ETag>\s*\"?([0-9a-fA-F]+)\"?\s*</ETag>",
                           blk)
            if pn:
                listed.append((int(pn.group(1)),
                               et.group(1).lower() if et else None))
        pfx = f"up:{upload_id}:"
        have = {int(k[len(pfx):]): json.loads(v)
                for k, v in idx.items() if k.startswith(pfx)}
        order = [n for n, _ in listed] or sorted(have)
        if not order or any(n not in have for n in order):
            return "400 Bad Request", "application/xml", \
                b"<Error><Code>InvalidPart</Code></Error>", {}
        # a client-supplied ETag must match the stored part's
        if any(e is not None and e != have[n]["etag"]
               for n, e in listed):
            return "400 Bad Request", "application/xml", \
                b"<Error><Code>InvalidPart</Code></Error>", {}
        # parts must be listed in strictly ascending order, no dups
        if any(b <= a for a, b in zip(order, order[1:])):
            return "400 Bad Request", "application/xml", \
                b"<Error><Code>InvalidPartOrder</Code></Error>", {}
        parts = [[_part_obj(bucket, upload_id, n), have[n]["size"]]
                 for n in order]
        total = sum(s for _, s in parts)
        md5s = b"".join(bytes.fromhex(have[n]["etag"]) for n in order)
        etag = f"{hashlib.md5(md5s).hexdigest()}-{len(order)}"
        await self._drop_manifest(bucket, key, idx)  # overwrite semantics
        try:
            # a simple object previously at this key is replaced by the
            # manifest — free its base RADOS object too
            await self.ioctx.remove(_obj(bucket, key))
        except ObjectOperationError:
            pass
        await self.ioctx.set_omap(
            _index(bucket), f"m:{key}",
            json.dumps({"parts": parts, "etag": etag}).encode())
        await self.ioctx.set_omap(_index(bucket), f"k:{key}",
                                  total.to_bytes(8, "little"))
        try:     # like plain PUT: replacing the object clears any
            await self.ioctx.rm_omap_key(     # stale per-object acl
                _index(bucket), f"a:{key}")
        except ObjectOperationError:
            pass
        # drop upload bookkeeping (parts live on, referenced by the
        # manifest); unlisted parts are garbage-collected now
        for n in sorted(have):
            if n not in order:
                try:
                    await self.ioctx.remove(
                        _part_obj(bucket, upload_id, n))
                except ObjectOperationError:
                    pass
            await self.ioctx.rm_omap_key(_index(bucket),
                                         f"up:{upload_id}:{n:05d}")
        await self.ioctx.rm_omap_key(_index(bucket), f"u:{upload_id}")
        xml = (f'<?xml version="1.0"?><CompleteMultipartUploadResult>'
               f"<Bucket>{escape(bucket)}</Bucket>"
               f"<Key>{escape(key)}</Key>"
               f'<ETag>"{etag}"</ETag>'
               f"</CompleteMultipartUploadResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    async def _abort_multipart(self, bucket: str, key: str,
                               upload_id: str):
        meta, idx = await self._upload_meta(bucket, upload_id)
        if meta is None or meta["key"] != key:
            return "404 Not Found", "application/xml", \
                b"<Error><Code>NoSuchUpload</Code></Error>", {}
        pfx = f"up:{upload_id}:"
        for k in idx:
            if k.startswith(pfx):
                n = int(k[len(pfx):])
                try:
                    await self.ioctx.remove(
                        _part_obj(bucket, upload_id, n))
                except ObjectOperationError:
                    pass
                await self.ioctx.rm_omap_key(_index(bucket), k)
        await self.ioctx.rm_omap_key(_index(bucket), f"u:{upload_id}")
        return "204 No Content", "application/xml", b"", {}
