"""The ``plugin=jax`` erasure-code backend — RS encode/decode on TPU.

The north-star component: implements the ErasureCodeInterface contract with
GF(2^8) Reed-Solomon realized as batched binary matmuls on the MXU (or
nibble-LUT gathers on the VPU), replacing the reference's SIMD region kernels
(ref: src/erasure-code/isa/ErasureCodeIsa.cc ErasureCodeIsa;
src/erasure-code/jerasure/ErasureCodeJerasure.cc).

Per-erasure-pattern decode matrices are inverted once host-side and cached,
mirroring the reference's expanded-table cache
(ref: src/erasure-code/isa/ErasureCodeIsaTableCache.cc).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec import matrix as rs
from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.gf import ops, tables
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")


class _MatrixKernel:
    """A GF coding matrix compiled for both TPU formulations."""

    def __init__(self, coeffs: np.ndarray, backend: str):
        self.coeffs = np.asarray(coeffs, dtype=np.uint8)
        self.backend = backend
        self.bitmatrix = jnp.asarray(
            tables.expand_bitmatrix(self.coeffs), dtype=jnp.int8)
        lo, hi = tables.nibble_tables(self.coeffs)
        self.lo = jnp.asarray(lo)
        self.hi = jnp.asarray(hi)

    def apply(self, data: jax.Array) -> jax.Array:
        """(rows_in, L) uint8 -> (rows_out, L) uint8."""
        if self.backend == "lut":
            return ops.gf_matmul_lut(self.lo, self.hi, data)
        return ops.gf_matmul_bitplanes(self.bitmatrix, data)

    def apply_batch(self, data: jax.Array) -> jax.Array:
        """(batch, rows_in, C) -> (batch, rows_out, C)."""
        return ops.encode_stripes(self.bitmatrix, self.lo, self.hi, data,
                                  backend="lut" if self.backend == "lut"
                                  else "bitmatmul")


class ErasureCodeJax(ErasureCodeInterface):
    """plugin=jax technique={reed_sol_van,cauchy_orig,cauchy_good} k=K m=M"""

    DEFAULT_TECHNIQUE = "reed_sol_van"

    def __init__(self, profile: ErasureCodeProfile | str | None = None,
                 backend: str = "auto"):
        super().__init__()
        self.technique = self.DEFAULT_TECHNIQUE
        self.backend = backend
        self._encode_kernel: _MatrixKernel | None = None
        self._decode_cache: dict[tuple, _MatrixKernel] = {}
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    # -- lifecycle --------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 2)
        self.m = profile.get_int("m", 2)
        self.technique = profile.get("technique", self.DEFAULT_TECHNIQUE)
        self.backend = profile.get("backend", self.backend)
        if self.k < 1 or self.m < 1:
            raise ValueError(f"invalid geometry k={self.k} m={self.m}")
        if self.backend == "auto":
            # bitmatmul rides the MXU; the LUT path wins only for tiny
            # batches where matmul padding dominates (measured on TPU).
            self.backend = "bitmatmul"
        if self.backend not in ("bitmatmul", "lut"):
            raise ValueError(f"unknown backend {self.backend!r}; "
                             f"supported: bitmatmul, lut, auto")
        coeffs = rs.coding_matrix(self.technique, self.k, self.m)
        self._encode_kernel = _MatrixKernel(coeffs, self.backend)
        self._decode_cache.clear()
        log.dout(5, "init", k=self.k, m=self.m, technique=self.technique,
                 backend=self.backend)

    def is_mds(self) -> bool:
        return True

    # -- encode -----------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = jnp.asarray(data, dtype=jnp.uint8)
        return np.asarray(self._encode_kernel.apply(data))

    def encode_batch(self, data: jax.Array) -> jax.Array:
        """Batched TPU path: (batch, k, C) uint8 -> (batch, m, C) parity.

        Stays on device; the benchmark and the sharded pipeline call this.
        """
        return self._encode_kernel.apply_batch(data)

    # -- decode -----------------------------------------------------------
    def _decode_kernel(self, avail: tuple[int, ...],
                       want: tuple[int, ...]) -> _MatrixKernel:
        key = (avail, want)
        kern = self._decode_cache.get(key)
        if kern is None:
            d = rs.decode_matrix(self.technique, self.k, self.m, avail, want)
            kern = _MatrixKernel(d, self.backend)
            self._decode_cache[key] = kern
            log.dout(10, "decode matrix built", avail=avail, want=want)
        return kern

    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        avail = tuple(sorted(chunks))[:self.k]
        if len(avail) < self.k:
            raise ValueError(
                f"cannot decode: have {len(chunks)} chunks, need {self.k}")
        want_t = tuple(want)
        kern = self._decode_kernel(avail, want_t)
        stacked = jnp.stack(
            [jnp.asarray(chunks[i], dtype=jnp.uint8) for i in avail])
        out = np.asarray(kern.apply(stacked))
        return {c: out[i] for i, c in enumerate(want_t)}

    def decode_batch(self, want: Sequence[int], avail: Sequence[int],
                     chunks: jax.Array) -> jax.Array:
        """Batched decode: chunks (batch, len(avail), C) -> (batch, len(want), C)."""
        kern = self._decode_kernel(tuple(avail), tuple(want))
        return kern.apply_batch(chunks)
