"""The ``plugin=jax`` erasure-code backend — RS encode/decode on TPU.

The north-star component: implements the ErasureCodeInterface contract with
GF(2^8) Reed-Solomon realized as batched binary matmuls on the MXU (or
nibble-LUT gathers on the VPU), replacing the reference's SIMD region kernels
(ref: src/erasure-code/isa/ErasureCodeIsa.cc ErasureCodeIsa;
src/erasure-code/jerasure/ErasureCodeJerasure.cc).

Per-erasure-pattern decode matrices are inverted once host-side and cached,
mirroring the reference's expanded-table cache
(ref: src/erasure-code/isa/ErasureCodeIsaTableCache.cc).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec import matrix as rs
from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.gf import ops, tables
from ceph_tpu.gf import pallas_kernels as pk
from ceph_tpu.utils.devmon import devmon as _devmon
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")


class _MatrixKernel:
    """A GF coding matrix compiled for the TPU formulations.

    backend "pallas" uses the fused unpack+matmul+pack kernel
    (gf.pallas_kernels) when the chunk length is tile-aligned, falling
    back to the XLA bitmatmul otherwise; the encode plan (bit-major
    permuted matrix + pack weights) is built host-side here, mirroring
    the reference's expanded-table construction at init
    (ref: src/erasure-code/isa/ErasureCodeIsa.cc prepare)."""

    def __init__(self, coeffs: np.ndarray, backend: str):
        self.coeffs = np.asarray(coeffs, dtype=np.uint8)
        self.backend = backend
        bm_np = tables.expand_bitmatrix(self.coeffs)
        self.bitmatrix = jnp.asarray(bm_np, dtype=jnp.int8)
        lo, hi = tables.nibble_tables(self.coeffs)
        self.lo = jnp.asarray(lo)
        self.hi = jnp.asarray(hi)
        self.plan = pk.make_plan(bm_np) if pk.HAVE_PALLAS else None

    def apply(self, data: jax.Array) -> jax.Array:
        """(rows_in, L) uint8 -> (rows_out, L) uint8."""
        if self.backend == "lut":
            return ops.gf_matmul_lut(self.lo, self.hi, data)
        if self.backend == "pallas" and self.plan is not None \
                and pk.pallas_ok(int(data.shape[-1])):
            return pk.encode_batch_planned(
                self.plan, data[None],
                interpret=jax.default_backend() != "tpu")[0]
        return ops.gf_matmul_bitplanes(self.bitmatrix, data)

    def apply_batch(self, data: jax.Array) -> jax.Array:
        """(batch, rows_in, C) -> (batch, rows_out, C)."""
        if self.backend == "pallas" and self.plan is not None \
                and pk.pallas_ok(int(data.shape[-1])):
            return pk.encode_batch_planned(
                self.plan, data,
                interpret=jax.default_backend() != "tpu")
        return ops.encode_stripes(self.bitmatrix, self.lo, self.hi, data,
                                  backend="lut" if self.backend == "lut"
                                  else "bitmatmul")


class _BitmatrixKernel:
    """A raw GF(2) bitmatrix (array code) compiled for the MXU: operates
    on w packets per chunk (ref: jerasure bitmatrix techniques)."""

    def __init__(self, bm: np.ndarray, w: int):
        self.bm = jnp.asarray(np.asarray(bm, dtype=np.int8))
        self.w = w

    def apply_batch(self, data: jax.Array) -> jax.Array:
        """(batch, drives_in, C) -> (batch, drives_out, C); C % w == 0."""
        return ops.bitmatrix_encode_stripes(self.bm, data, self.w)

    def apply(self, data: jax.Array) -> jax.Array:
        return self.apply_batch(data[None])[0]


class ErasureCodeJax(ErasureCodeInterface):
    """plugin=jax k=K m=M technique= reed_sol_van | reed_sol_r6_op |
    cauchy_orig | cauchy_good | liberation | blaum_roth | liber8tion

    GF(2^8) techniques run as (8m)x(8k) bit-plane matmuls; the bitmatrix
    (array-code) techniques run as (2w)x(kw) packet-plane matmuls — both
    land on the MXU, so jerasure's XOR-schedule machinery (whose entire
    point is CPU XOR minimality) has no analog here by design."""

    DEFAULT_TECHNIQUE = "reed_sol_van"

    def __init__(self, profile: ErasureCodeProfile | str | None = None,
                 backend: str = "auto"):
        super().__init__()
        self.technique = self.DEFAULT_TECHNIQUE
        self.backend = backend
        self.w = 8
        self._bitmatrix = None
        self._encode_kernel = None
        self._decode_cache: dict[tuple, object] = {}
        self._decode_ref_cache: dict[tuple, np.ndarray] = {}
        self._fused_crc_cache: dict[int, object] = {}
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    # -- lifecycle --------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 2)
        self.m = profile.get_int("m", 2)
        self.technique = profile.get("technique", self.DEFAULT_TECHNIQUE)
        self.backend = profile.get("backend", self.backend)
        if self.k < 1 or self.m < 1:
            raise ValueError(f"invalid geometry k={self.k} m={self.m}")
        if self.backend == "auto":
            # The fused pallas kernel wins on real TPUs (~103 GiB/s
            # encode at k=8,m=3 on v5e after the round-4 mod-2-absorb /
            # block-diag rewrite, vs ~60 for the XLA bitmatmul); on CPU
            # it only runs in slow interpret mode, so default to the
            # XLA path there.
            self.backend = ("pallas" if pk.HAVE_PALLAS
                            and jax.default_backend() == "tpu"
                            else "bitmatmul")
        if self.backend not in ("bitmatmul", "lut", "pallas"):
            raise ValueError(f"unknown backend {self.backend!r}; "
                             f"supported: bitmatmul, lut, pallas, auto")
        if self.technique in rs.BITMATRIX_TECHNIQUES:
            from ceph_tpu.ec import bitmatrix as bmx
            self.w = profile.get_int("w", 0) or bmx.default_w(
                self.technique, self.k)
            self._bitmatrix = bmx.bitmatrix_for(self.technique, self.k,
                                                self.m, self.w)
            self._encode_kernel = _BitmatrixKernel(self._bitmatrix, self.w)
        else:
            self.w = 8
            self._bitmatrix = None
            coeffs = rs.coding_matrix(self.technique, self.k, self.m)
            self._encode_kernel = _MatrixKernel(coeffs, self.backend)
        self._decode_cache.clear()
        self._decode_ref_cache.clear()
        self._fused_crc_cache.clear()
        log.dout(5, "init", k=self.k, m=self.m, technique=self.technique,
                 backend=self.backend)

    def get_alignment(self) -> int:
        # bitmatrix chunks are w packets; keep packets lane-aligned
        # (lcm, not product: w=8 already divides the lane width)
        import math

        from ceph_tpu.ec.interface import DEFAULT_ALIGNMENT
        if self._bitmatrix is not None:
            return DEFAULT_ALIGNMENT * self.w // math.gcd(
                DEFAULT_ALIGNMENT, self.w)
        return DEFAULT_ALIGNMENT

    def is_mds(self) -> bool:
        return True

    # -- encode -----------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = jnp.asarray(data, dtype=jnp.uint8)
        return np.asarray(self._encode_kernel.apply(data))

    def encode_batch(self, data: jax.Array) -> jax.Array:
        """Batched TPU path: (batch, k, C) uint8 -> (batch, m, C) parity.

        Stays on device; the benchmark and the sharded pipeline call
        this. First call per (kernel, shape) is compile-accounted
        through the device-runtime monitor (round 14) — a new batch
        shape recompiling under the OSD aggregator is a countable,
        traceable event now."""
        kern = self._encode_kernel
        return _devmon().jit_call(
            "ec_encode", (id(kern), tuple(data.shape)),
            kern.apply_batch, data)

    def encode_batch_reference(self, data):
        """Host-only bit-exact reference encode — the last rung of the
        OSD aggregator's degrade ladder. Pure numpy, no jit, no
        device: ``gf_matmul_np`` (the numpy oracle both JAX kernels
        are pinned against) for the GF(2^8) techniques, and the
        packet-plane XOR mirror of ``bitmatrix_encode_stripes`` for
        the array codes. (B, k, C) uint8 -> (B, m, C)."""
        data = np.ascontiguousarray(np.asarray(data), dtype=np.uint8)
        B, k, C = data.shape
        if self._bitmatrix is not None:
            w = self.w
            ps = C // w
            bm = np.asarray(self._bitmatrix) != 0         # (mw, kw)
            planes = data.reshape(B, k * w, ps)
            flat = planes.transpose(1, 0, 2).reshape(k * w, B * ps)
            out = np.zeros((bm.shape[0], B * ps), dtype=np.uint8)
            for r in range(bm.shape[0]):
                sel = flat[bm[r]]
                if sel.shape[0]:
                    out[r] = np.bitwise_xor.reduce(sel, axis=0)
            mw = out.shape[0]
            return out.reshape(mw, B, ps).transpose(1, 0, 2).reshape(
                B, mw // w, C)
        coeffs = self._encode_kernel.coeffs
        x = data.transpose(1, 0, 2)                       # (k, B, C)
        return tables.gf_matmul_np(coeffs, x).transpose(1, 0, 2)

    def encode_batch_with_crc(self, data):
        """Fused checksum+encode: ONE jitted device program computes
        the parity AND a raw-CRC32 per shard row (data rows included).

        (B, k, C) uint8 -> (parity (B, m, C), row_crcs (B, k+m) u32).
        The CRC leg is the (rows, 8C) @ (8C, 32) GF(2) bit matmul of
        ec.crc.row_crc_matrix — same MXU bit-plane idiom as the encode
        itself; the per-shard combine over a write's rows is O(rows)
        32-bit host work in ec.crc (the O(bytes) part lives here)."""
        from ceph_tpu.ec import crc as _crc

        data = jnp.asarray(data, dtype=jnp.uint8)
        C = int(data.shape[-1])
        fused = self._fused_crc_cache.get(C)
        if fused is None:
            G = jnp.asarray(_crc.row_crc_matrix(C))       # (8C, 32) i8
            kern = self._encode_kernel
            n = self.k + self.m

            def _fused(d):
                parity = kern.apply_batch(d)
                word = jnp.concatenate(
                    [d, parity.astype(jnp.uint8)], axis=1)  # (B, n, C)
                rows = word.reshape(-1, C)
                # one bit-PLANE at a time: (rows, C) @ (C, 32) per
                # plane keeps the matmul operand at word-bytes size —
                # the naive (rows, 8C) bit expansion is 8x the batch
                # (~1.4 GiB at the osd_ec_agg_max_stripes ceiling on
                # the production shape) and would break that knob's
                # memory-bound promise. G row 8p+b is byte p, bit b
                # (LSB-first, matching row_crc_matrix), so plane b
                # multiplies G[b::8].
                acc = jnp.zeros((rows.shape[0], 32), dtype=jnp.int32)
                for b in range(8):
                    plane = ((rows >> jnp.uint8(b)) &
                             jnp.uint8(1)).astype(jnp.int8)
                    acc = acc + jnp.matmul(
                        plane, G[b::8, :],
                        preferred_element_type=jnp.int32)
                bit32 = (acc & 1).astype(jnp.uint32)
                weights = jnp.uint32(1) << jnp.arange(
                    32, dtype=jnp.uint32)
                crcs = jnp.sum(bit32 * weights[None, :], axis=1,
                               dtype=jnp.uint32)
                return parity, crcs.reshape(-1, n)

            fused = self._fused_crc_cache[C] = jax.jit(_fused)
        return _devmon().jit_call(
            "ec_encode_crc", (id(fused), tuple(data.shape)),
            fused, data)

    # -- decode -----------------------------------------------------------
    def _decode_kernel(self, avail: tuple[int, ...],
                       want: tuple[int, ...]):
        key = (avail, want)
        kern = self._decode_cache.get(key)
        if kern is None:
            if self._bitmatrix is not None:
                from ceph_tpu.ec import bitmatrix as bmx
                d = bmx.decode_bitmatrix(self._bitmatrix, self.k, self.m,
                                         self.w, avail, want)
                kern = _BitmatrixKernel(d, self.w)
            else:
                d = rs.decode_matrix(self.technique, self.k, self.m,
                                     avail, want)
                kern = _MatrixKernel(d, self.backend)
            self._decode_cache[key] = kern
            log.dout(10, "decode matrix built", avail=avail, want=want)
        return kern

    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        avail = tuple(sorted(chunks))[:self.k]
        if len(avail) < self.k:
            raise ValueError(
                f"cannot decode: have {len(chunks)} chunks, need {self.k}")
        want_t = tuple(want)
        kern = self._decode_kernel(avail, want_t)
        stacked = jnp.stack(
            [jnp.asarray(chunks[i], dtype=jnp.uint8) for i in avail])
        out = np.asarray(kern.apply(stacked))
        return {c: out[i] for i, c in enumerate(want_t)}

    def decode_batch(self, want: Sequence[int], avail: Sequence[int],
                     chunks: jax.Array) -> jax.Array:
        """Batched decode: chunks (batch, len(avail), C) -> (batch, len(want), C)."""
        kern = self._decode_kernel(tuple(avail), tuple(want))
        return _devmon().jit_call(
            "ec_decode", (id(kern), tuple(chunks.shape)),
            kern.apply_batch, chunks)

    def decode_batch_reference(self, want: Sequence[int],
                               avail: Sequence[int], chunks):
        """Host-only bit-exact reference decode — the last rung of the
        OSD read aggregator's degrade ladder. Pure numpy, no jit, no
        device: the same per-erasure-pattern matrix inversion the
        device path caches, applied with ``gf_matmul_np`` (GF(2^8)
        techniques) or the packet-plane XOR mirror (array codes).
        (B, len(avail), C) uint8 -> (B, len(want), C)."""
        chunks = np.ascontiguousarray(np.asarray(chunks), dtype=np.uint8)
        B, _, C = chunks.shape
        key = (tuple(avail), tuple(want))
        d = self._decode_ref_cache.get(key)
        if d is None:
            if self._bitmatrix is not None:
                from ceph_tpu.ec import bitmatrix as bmx
                d = bmx.decode_bitmatrix(self._bitmatrix, self.k, self.m,
                                         self.w, key[0], key[1])
            else:
                d = rs.decode_matrix(self.technique, self.k, self.m,
                                     key[0], key[1])
            self._decode_ref_cache[key] = np.asarray(d, dtype=np.uint8)
            d = self._decode_ref_cache[key]
        if self._bitmatrix is not None:
            w = self.w
            ps = C // w
            bm = d != 0                        # (len(want)*w, len(avail)*w)
            planes = chunks.reshape(B, -1, ps)
            flat = planes.transpose(1, 0, 2).reshape(-1, B * ps)
            out = np.zeros((bm.shape[0], B * ps), dtype=np.uint8)
            for r in range(bm.shape[0]):
                sel = flat[bm[r]]
                if sel.shape[0]:
                    out[r] = np.bitwise_xor.reduce(sel, axis=0)
            ww = out.shape[0]
            return out.reshape(ww, B, ps).transpose(1, 0, 2).reshape(
                B, ww // w, C)
        x = chunks.transpose(1, 0, 2)          # (len(avail), B, C)
        return tables.gf_matmul_np(d, x).transpose(1, 0, 2)


def _resident_perf():
    """Per-OSD counter family for the hot-shard residency cache
    (register=False: several in-process OSDs each own one; they reach
    prometheus through the daemon->mgr report path as
    ``ceph_osd_ec_resident_*`` rows)."""
    from ceph_tpu.utils.perf_counters import PerfCountersBuilder
    return (
        PerfCountersBuilder("osd_ec_resident")
        .add_u64_counter("hits",
                         "gathers served from the device-resident "
                         "cache (no subreads, no decode, no H2D)")
        .add_u64_counter("misses", "gathers that went to the shards")
        .add_u64_counter("inserts", "stripe ranges staged resident")
        .add_u64_counter("evictions",
                         "LRU evictions under osd_ec_resident_bytes")
        .add_u64_counter("invalidations",
                         "entries dropped by a write to their object")
        .add_u64_counter("rejected",
                         "ranges larger than the whole budget, never "
                         "cached")
        .add_u64("resident_bytes", "bytes currently resident (gauge)")
        .add_u64("entries", "entries currently resident (gauge)")
        .create_perf_counters(register=False))


class DeviceShardCache:
    """Bounded device-side LRU of gathered stripe ranges — hot-shard
    residency for the OSD data path (round 19).

    A read-modify-write or a repeated degraded read used to re-gather
    the same stripes (k subread round-trips + a decode + an H2D stage)
    every time. This cache pins the gathered (count, k, C) batch in
    device memory under an ``osd_ec_resident_bytes`` budget, keyed by
    (PG, object, stripe range, object VERSION) — the same write-time
    ``_v`` discipline the shards carry, so any write bumps the version
    and makes every cached generation of that object unreachable.
    Explicit ``invalidate`` on sub-write apply reclaims those dead
    entries eagerly instead of waiting for LRU pressure.

    Entries are immutable by contract: ``get`` returns the stored
    device array; callers read through ``np.asarray`` or feed it to a
    device kernel, never mutate it in place.
    """

    def __init__(self, config: dict | None = None):
        self.config = config if config is not None else {}
        self.perf = _resident_perf()
        # key -> (device array, nbytes); insertion order = LRU order
        self._lru: "dict[tuple, tuple[object, int]]" = {}
        self._bytes = 0

    # knobs (read LIVE: shrinking the budget takes effect on the next
    # insert's eviction sweep; 0 disables lookups AND inserts)
    def budget(self) -> int:
        return int(self.config.get("osd_ec_resident_bytes", 64 << 20))

    def enabled(self) -> bool:
        return self.budget() > 0

    def get(self, key: tuple):
        if not self.enabled():
            return None
        ent = self._lru.get(key)
        if ent is None:
            self.perf.inc("misses")
            return None
        # move-to-end = most recently used
        del self._lru[key]
        self._lru[key] = ent
        self.perf.inc("hits")
        return ent[0]

    def put(self, key: tuple, host_array) -> None:
        if not self.enabled() or key in self._lru:
            return
        # explicit copy: jax.device_put may alias an aligned host
        # buffer on the CPU backend, and callers keep (and may write
        # through copies of) the array they handed us
        arr = np.array(host_array, dtype=np.uint8, order="C")
        nbytes = int(arr.nbytes)
        budget = self.budget()
        if nbytes > budget:
            self.perf.inc("rejected")
            return
        while self._bytes + nbytes > budget and self._lru:
            old_key = next(iter(self._lru))
            _, old_n = self._lru.pop(old_key)
            self._bytes -= old_n
            self.perf.inc("evictions")
        try:
            dev = jax.device_put(arr)
        except Exception as e:
            log.dout(1, f"resident cache device_put failed "
                        f"({type(e).__name__}: {str(e)[:200]})")
            return
        self._lru[key] = (dev, nbytes)
        self._bytes += nbytes
        self.perf.inc("inserts")
        self._gauges()

    def invalidate(self, *prefix) -> int:
        """Drop every entry whose key starts with ``prefix`` (e.g.
        (pgid, oid) on a sub-write apply). Version-keying already makes
        stale generations unreachable; this reclaims their bytes."""
        n = 0
        for key in [k for k in self._lru if k[:len(prefix)] == prefix]:
            _, nbytes = self._lru.pop(key)
            self._bytes -= nbytes
            n += 1
        if n:
            self.perf.inc("invalidations", n)
            self._gauges()
        return n

    def clear(self) -> None:
        self._lru.clear()
        self._bytes = 0
        self._gauges()

    def _gauges(self) -> None:
        self.perf.set("resident_bytes", self._bytes)
        self.perf.set("entries", len(self._lru))

    def dump(self) -> dict:
        d = self.perf.dump()
        return {
            "enabled": self.enabled(),
            "budget_bytes": self.budget(),
            "resident_bytes": self._bytes,
            "entries": len(self._lru),
            "hits": d.get("hits", 0),
            "misses": d.get("misses", 0),
            "inserts": d.get("inserts", 0),
            "evictions": d.get("evictions", 0),
            "invalidations": d.get("invalidations", 0),
        }


class StreamingEncodePipeline:
    """Double-buffered H2D/D2H streaming encode.

    The resident benchmark number assumes the stripes already live in
    HBM; a real ingest path pays host->device per batch. This pipeline
    overlaps the three legs so a real host measures the PCIe(-or-
    tunnel)-bound rate instead of the dispatch-serialized one:

    - **H2D of batch N+1** (``jax.device_put``, asynchronous) is issued
      BEFORE batch N's encode is dispatched, so the transfer engine
      fills the next buffer while the MXU works;
    - **encode of batch N** runs under a jit whose input buffer is
      DONATED on TPU (``donate_argnums``) — with two in-flight host
      batches the donated buffers alternate ping/pong, so steady state
      holds two staging buffers instead of allocating per step;
    - **D2H of batch N-1** (the ``np.asarray`` readback) blocks the
      host while batch N executes — in-order device execution makes
      the previous result's readback the natural overlap window.

    Donation is gated to the TPU backend: the CPU runtime ignores
    donations with a per-call warning, which would spam every streamed
    smoke run.
    """

    def __init__(self, ec: ErasureCodeJax, donate: bool | None = None):
        self.ec = ec
        if donate is None:
            donate = jax.default_backend() == "tpu"
        kern = ec._encode_kernel
        self._kern = kern
        self._fn = jax.jit(kern.apply_batch,
                           donate_argnums=(0,) if donate else ())
        # lazily-built non-donated fallback jit (see encode_iter)
        self._plain_fn = None

    def _encode_plain(self, host, dm):
        """The non-donated unpipelined fallback: stage, encode, read
        back — one batch at a time, no buffer donation, no overlap."""
        if self._plain_fn is None:
            self._plain_fn = jax.jit(self._kern.apply_batch)
        fn = self._plain_fn
        out = dm.jit_call("ec_stream_encode",
                          (id(fn), tuple(host.shape)), fn, host)
        host_out = np.asarray(out)
        dm.record_d2h(host_out.nbytes)
        return host_out

    def encode_iter(self, batches):
        """host (B, k, C) uint8 batches in -> parity np arrays out,
        transfer of batch N+1 overlapped with encode of batch N.

        Transfer accounting (round 14): every H2D stage and D2H
        readback feeds the device-runtime monitor's byte counters, so
        a pipeline-bound ingest shows up as transfer GiB in
        `device-runtime status` instead of as unexplained wall.

        Fault discipline (round 16): a transfer/encode failure
        mid-pipeline does NOT lose batches — every staged host batch
        is kept until its parity is yielded, so on failure the
        pipeline falls back to the non-donated unpipelined path,
        re-encodes the in-flight batches from their host copies and
        drains the rest of the iterator (devmon counts a
        ``stream_fallbacks``)."""
        dm = _devmon()

        def _encode(batch):
            return dm.jit_call("ec_stream_encode",
                               (id(self._fn), tuple(batch.shape)),
                               self._fn, batch)

        def _readback(parity):
            host = np.asarray(parity)
            dm.record_d2h(host.nbytes)
            return host

        it = iter(batches)
        # host copies of staged batches whose parity has NOT been
        # yielded yet, oldest first — the fallback's replay source
        pending: list[np.ndarray] = []
        try:
            try:
                first = np.ascontiguousarray(next(it))
            except StopIteration:
                return
            pending.append(first)
            dm.record_h2d(first.nbytes)
            dm.note_staging(first.nbytes)
            cur = jax.device_put(first)
            prev = None
            for nxt_host in it:
                nxt_host = np.ascontiguousarray(nxt_host)
                pending.append(nxt_host)
                dm.record_h2d(nxt_host.nbytes)
                nxt = jax.device_put(nxt_host)
                out = _encode(cur)
                if prev is not None:
                    yield _readback(prev)
                    pending.pop(0)
                prev, cur = out, nxt
            out = _encode(cur)
            if prev is not None:
                yield _readback(prev)
                pending.pop(0)
            yield _readback(out)
            pending.pop(0)
        except Exception as e:
            dm.perf.inc("stream_fallbacks")
            log.dout(0, f"streaming encode pipeline failed "
                        f"({type(e).__name__}: {str(e)[:200]}) — "
                        f"falling back to the unpipelined path for "
                        f"{len(pending)} in-flight batches + the rest")
            for host in pending:
                yield self._encode_plain(host, dm)
            for nxt_host in it:
                host = np.ascontiguousarray(nxt_host)
                dm.record_h2d(host.nbytes)
                yield self._encode_plain(host, dm)

    def encode_all(self, batches) -> list:
        return list(self.encode_iter(batches))

    def encode_payload_iter(self, payloads, k: int, chunk_size: int):
        """Messenger-ingest handoff: wire-frame payload buffers in,
        parity out, with NO intermediate host staging copy.

        Each payload is whatever the messenger delivered for a write —
        ``bytes`` or, on the zero-copy decode path (denc blob_view), a
        ``memoryview`` over the received frame — whose length is a
        multiple of the stripe width k*chunk_size. ``np.frombuffer``
        wraps the buffer in place and the reshape is a view, so the
        bytes go wire frame -> H2D stage (encode_iter's device_put)
        directly; the old path staged a full ``bytes`` copy first."""
        W = k * chunk_size

        def _carve():
            for p in payloads:
                arr = np.frombuffer(p, dtype=np.uint8)
                if arr.size % W:
                    raise ValueError(
                        f"payload of {arr.size} bytes is not a whole "
                        f"number of {W}-byte stripes")
                yield arr.reshape(-1, k, chunk_size)
        return self.encode_iter(_carve())
