"""Erasure coding behind the reference's ErasureCodeInterface contract.

(ref: src/erasure-code/ErasureCodeInterface.h; src/erasure-code/ErasureCode.cc
base class; src/erasure-code/ErasureCodePlugin.cc registry.)

The compute path is JAX on TPU (``plugin=jax``); profiles use the reference's
``plugin=... technique=... k=... m=...`` key=value syntax so benchmark
invocations carry over verbatim.
"""

from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.ec.registry import ErasureCodePluginRegistry, factory
from ceph_tpu.ec.jax_plugin import ErasureCodeJax
from ceph_tpu.ec import matrix
