"""CLAY — Coupled-LAYer MSR codes (repair-bandwidth-optimal).

ref: src/erasure-code/clay/ErasureCodeClay.{h,cc} and the FAST'18 paper
"Clay Codes: Moulding MDS Codes to Yield Vector Codes". Supported
geometry: d = k+m-1 helpers (the upstream default), so q = d-k+1 = m.

Structure: n = k+m nodes padded to n' = q*t grid nodes (virtual
"shortened" nodes hold zero chunks); every chunk is a vector of
alpha = q^t sub-chunks indexed by planes z in Z_q^t. Node (x, y) sits at
grid position y*q + x. Vertex (x,y;z) is *unpaired* when z_y == x;
otherwise it couples with vertex (z_y, y; z with z_y:=x) through the
symmetric pairwise transform

    C(v) = U(v) + gamma * U(partner(v))        [gamma^2 != 1]

where U is the uncoupled code: in every plane z, the U values across the
n' nodes form a codeword of a scalar (n', n'-m) MDS code.

- encode   = layered decode with the m parity nodes as erasures;
- decode   = layered decode (planes processed by Intersection Score);
- repair   = single failure (x*,y*) reads ONLY the alpha/q sub-chunks of
  planes {z : z_{y*} = x*} from each of the d = n-1 helpers, solving one
  m x m MDS system per plane — bandwidth (n-1)/m * alpha/q vs k*alpha,
  the whole point of the code.

Provenance: the reference tree was empty during the survey (SURVEY.md
warning); coupling coefficient and sub-chunk ordering are this
implementation's own conventions, property-verified (MDS + repair
bandwidth) rather than byte-matched.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

from ceph_tpu.ec import matrix as rs
from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.gf import tables
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")

GAMMA = 2  # coupling coefficient; needs gamma^2 != 1 in GF(2^8)


class ErasureCodeClay(ErasureCodeInterface):
    """plugin=clay k=K m=M (d=K+M-1) technique=reed_sol_van"""

    def __init__(self, profile: ErasureCodeProfile | str | None = None):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0      # virtual (shortened) nodes
        self.technique = "reed_sol_van"
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 4)
        self.m = profile.get_int("m", 2)
        self.d = profile.get_int("d", self.k + self.m - 1)
        self.technique = profile.get("technique", "reed_sol_van")
        if self.d != self.k + self.m - 1:
            raise NotImplementedError(
                f"clay: only d=k+m-1 supported (got d={self.d})")
        n = self.k + self.m
        self.q = self.d - self.k + 1      # == m
        self.t = -(-n // self.q)
        self.nu = self.q * self.t - n     # virtual nodes at grid tail
        nprime = self.q * self.t
        # plane MDS code: data = real data + virtual zeros, parity = m
        self.kprime = nprime - self.m
        self._coding = rs.coding_matrix(self.technique, self.kprime, self.m)
        self._inv_det = tables.gf_inv(1 ^ tables.gf_mul(GAMMA, GAMMA))
        self._decode_plane_cache: dict = {}
        log.dout(5, "clay init", k=self.k, m=self.m, d=self.d, q=self.q,
                 t=self.t, sub_chunks=self.sub_chunk_count())

    # -- geometry ---------------------------------------------------------
    def sub_chunk_count(self) -> int:
        """alpha = q^t (ref: ErasureCodeClay::get_sub_chunk_count)."""
        return self.q ** self.t

    def get_repair_sub_chunk_count(self) -> int:
        """Sub-chunks read per helper for one repair: alpha/q
        (ref: ErasureCodeClay::get_repair_sub_chunk_count)."""
        return self.sub_chunk_count() // self.q

    def get_alignment(self) -> int:
        return self.sub_chunk_count()

    def get_chunk_size(self, object_size: int) -> int:
        align = self.sub_chunk_count()
        chunk = -(-object_size // self.k)
        return -(-chunk // align) * align

    # -- grid helpers -----------------------------------------------------
    # grid node p = y*q + x; chunks: 0..k-1 data, k..n-1 parity,
    # n..n'-1 virtual. Plane z = digits (z_0..z_{t-1}), index
    # sum(z_y * q**y).
    def _plane_digits(self, zi: int) -> list[int]:
        out = []
        for _ in range(self.t):
            out.append(zi % self.q)
            zi //= self.q
        return out

    def _plane_index(self, digits: Sequence[int]) -> int:
        return sum(d * self.q ** y for y, d in enumerate(digits))

    def _plane_rows(self) -> tuple[list[int], list[int]]:
        """plane-code (data_rows, parity_rows) in grid order."""
        n = self.k + self.m
        nprime = self.q * self.t
        data = list(range(self.k)) + list(range(n, nprime))
        parity = list(range(self.k, n))
        return data, parity

    # -- pairwise transform ----------------------------------------------
    def _uncouple_pair(self, c_v, c_p):
        """U(v) from C(v), C(partner): U = (C(v) + g*C(p)) / (1 + g^2)."""
        return tables.gf_mul_np(
            self._inv_det, c_v ^ tables.gf_mul_np(GAMMA, c_p))

    # -- layered decode (the engine) --------------------------------------
    def _decode_layered(self, chunks: dict[int, np.ndarray],
                        erased: list[int], C: int) -> dict[int, np.ndarray]:
        """Recover C of erased nodes (<= m) from the others.

        chunks: node -> (C,) uint8 for all non-erased REAL nodes.
        Works on (n', alpha, S) sub-chunk tensors; plane sweep in
        Intersection-Score order, then per-plane MDS recovery of U,
        finally re-couple the erased nodes' C.
        """
        q, t = self.q, self.t
        nprime = q * t
        alpha = self.sub_chunk_count()
        S = C // alpha
        n = self.k + self.m
        cc = np.zeros((nprime, alpha, S), dtype=np.uint8)
        for p, buf in chunks.items():
            cc[p] = np.asarray(buf, dtype=np.uint8).reshape(alpha, S)
        erased_set = set(erased)
        if len(erased_set) > self.m:
            raise ValueError(f"clay: {len(erased_set)} erasures > m={self.m}")

        planes = [self._plane_digits(zi) for zi in range(alpha)]
        is_of = []
        for z in planes:
            s = sum(1 for y in range(t)
                    if z[y] + y * q in erased_set)
            is_of.append(s)
        order = sorted(range(alpha), key=lambda zi: is_of[zi])

        U = np.zeros_like(cc)
        u_known = np.zeros((nprime, alpha), dtype=bool)
        data_rows, parity_rows = self._plane_rows()
        row_order = data_rows + parity_rows  # plane-code row id -> grid
        code_id = {p: i for i, p in enumerate(row_order)}
        dec_cache: dict = {}
        for zi in order:
            z = planes[zi]
            # 1) uncouple the non-erased nodes
            for p in range(nprime):
                if p in erased_set:
                    continue
                x, y = p % q, p // q
                if z[y] == x:
                    U[p, zi] = cc[p, zi]
                else:
                    pp = z[y] + y * q
                    z2 = list(z)
                    z2[y] = x
                    zi2 = self._plane_index(z2)
                    if pp in erased_set:
                        # partner plane has lower IS: its U is recovered
                        assert u_known[pp, zi2]
                        U[p, zi] = cc[p, zi] ^ tables.gf_mul_np(
                            GAMMA, U[pp, zi2])
                    else:
                        U[p, zi] = self._uncouple_pair(cc[p, zi],
                                                       cc[pp, zi2])
                u_known[p, zi] = True
            # 2) MDS-recover U of erased nodes in this plane
            if erased_set:
                avail = tuple(code_id[p] for p in range(nprime)
                              if p not in erased_set)
                want = tuple(code_id[p] for p in sorted(erased_set))
                key = (avail, want)
                if key not in dec_cache:
                    dec_cache[key] = rs.decode_matrix(
                        self.technique, self.kprime, self.m,
                        avail, want)
                dmat = dec_cache[key]
                stacked = np.stack([U[p, zi] for p in range(nprime)
                                    if p not in erased_set])[:self.kprime]
                out = tables.gf_matmul_np(dmat[:, :self.kprime], stacked)
                for idx, p in enumerate(sorted(erased_set)):
                    U[p, zi] = out[idx]
                    u_known[p, zi] = True
        # 3) re-couple erased nodes
        result: dict[int, np.ndarray] = {}
        for p in sorted(erased_set):
            if p >= n:
                continue
            x, y = p % q, p // q
            outc = np.zeros((alpha, S), dtype=np.uint8)
            for zi in range(alpha):
                z = planes[zi]
                if z[y] == x:
                    outc[zi] = U[p, zi]
                else:
                    pp = z[y] + y * q
                    z2 = list(z)
                    z2[y] = x
                    zi2 = self._plane_index(z2)
                    outc[zi] = U[p, zi] ^ tables.gf_mul_np(
                        GAMMA, U[pp, zi2])
            result[p] = outc.reshape(-1)
        return result

    # -- bandwidth-optimal single repair ----------------------------------
    def repair_plane_indices(self, failed: int) -> list[int]:
        """The alpha/q planes each helper is read at:
        {z : z_{y*} = x*}."""
        x, y = failed % self.q, failed // self.q
        return [zi for zi in range(self.sub_chunk_count())
                if self._plane_digits(zi)[y] == x]

    def repair_chunk(self, failed: int,
                     helper_subchunks: Mapping[int, Mapping[int, np.ndarray]],
                     chunk_size: int) -> np.ndarray:
        """Reconstruct `failed` from helpers' repair-plane sub-chunks only.

        helper_subchunks: node -> {plane_index -> (S,) uint8}, for every
        real node != failed, at exactly repair_plane_indices(failed)
        (virtual nodes are implicit zeros). Per plane: uncouple all nodes
        outside row y*, then solve the m x m parity-check system whose
        unknowns are the q = m row-y* node values.
        """
        q, t = self.q, self.t
        nprime = q * t
        alpha = self.sub_chunk_count()
        S = chunk_size // alpha
        x_f, y_f = failed % q, failed // q
        R = self.repair_plane_indices(failed)
        rset = set(R)
        # full parity-check H (m, n') in grid order: H @ U(plane) = 0
        data_rows, parity_rows = self._plane_rows()
        H = np.zeros((self.m, nprime), dtype=np.uint8)
        for j, p in enumerate(data_rows):
            H[:, p] = self._coding[:, j]
        for i, p in enumerate(parity_rows):
            H[i, p] = 1

        def read(p, zi):
            if p >= self.k + self.m:
                return np.zeros(S, dtype=np.uint8)  # virtual
            return np.asarray(helper_subchunks[p][zi], dtype=np.uint8)

        row_nodes = [y_f * q + xx for xx in range(q)]  # unknown columns
        Hs_inv = tables.gf_matinv_np(H[:, row_nodes])
        ginv = tables.gf_inv(GAMMA)
        out = np.zeros((alpha, S), dtype=np.uint8)
        for zi in R:
            z = self._plane_digits(zi)
            # rhs = sum of H-coded U over all known (non-row-y*) nodes;
            # pairs of such nodes stay inside the repair planes.
            rhs = np.zeros((self.m, S), dtype=np.uint8)
            for p in range(nprime):
                if p // q == y_f:
                    continue
                x, y = p % q, p // q
                if z[y] == x:
                    u = read(p, zi)
                else:
                    pp = z[y] + y * q
                    z2 = list(z)
                    z2[y] = x
                    zi2 = self._plane_index(z2)
                    assert zi2 in rset, "partner outside repair planes"
                    u = self._uncouple_pair(read(p, zi), read(pp, zi2))
                for i in range(self.m):
                    if H[i, p]:
                        rhs[i] ^= tables.gf_mul_np(int(H[i, p]), u)
            u_row = tables.gf_matmul_np(Hs_inv, rhs)  # (q, S): row-y* U's
            # failed vertex is unpaired at repair planes: C = U.
            out[zi] = u_row[x_f]
            # Non-repair planes z2 = z(y_f -> xx), xx != x_f (each covered
            # exactly once over zi in R): the failed vertex at z2 pairs
            # with the row node (xx, y_f) at plane z, giving
            #   C(node xx @ z)   = U(node xx @ z) + g * U(failed @ z2)
            #   C(failed  @ z2)  = U(failed @ z2) + g * U(node xx @ z)
            # (virtual row nodes work too: their C reads as zero).
            for xx in range(q):
                if xx == x_f:
                    continue
                z2 = list(z)
                z2[y_f] = xx
                zi2 = self._plane_index(z2)
                c_helper = read(y_f * q + xx, zi)
                u_helper = u_row[xx]
                u_failed_z2 = tables.gf_mul_np(ginv, c_helper ^ u_helper)
                out[zi2] = u_failed_z2 ^ tables.gf_mul_np(GAMMA, u_helper)
        return out.reshape(-1)

    # -- interface kernels ------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        C = data.shape[1]
        chunks = {i: data[i] for i in range(self.k)}
        out = self._decode_layered(
            chunks, list(range(self.k, self.k + self.m)), C)
        return np.stack([out[self.k + i] for i in range(self.m)])

    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        n = self.k + self.m
        have = {i: np.asarray(c, dtype=np.uint8) for i, c in chunks.items()}
        missing = sorted(set(range(n)) - set(have))
        C = next(iter(have.values())).shape[0]
        if len(have) < self.k:
            raise ValueError(
                f"clay: need {self.k} chunks, have {len(have)}")
        if not missing:
            return {i: have[i] for i in want}
        if len(missing) == 1 and len(have) == n - 1:
            # bandwidth-optimal path (reads only alpha/q per helper)
            failed = missing[0]
            R = self.repair_plane_indices(failed)
            alpha = self.sub_chunk_count()
            S = C // alpha
            subs = {p: {zi: have[p].reshape(alpha, S)[zi] for zi in R}
                    for p in have}
            rec = {failed: self.repair_chunk(failed, subs, C)}
        else:
            rec = self._decode_layered(have, missing, C)
        out = {}
        for i in want:
            out[i] = have[i] if i in have else rec[i]
        return out

    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> set[int]:
        """Single failure: all d = n-1 helpers (each read at only
        alpha/q sub-chunks — fewer BYTES than any k full chunks);
        otherwise any k (ref: ErasureCodeClay::minimum_to_decode)."""
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        n = self.k + self.m
        missing = set(range(n)) - avail
        if len(missing) == 1 and len(avail) == n - 1:
            return avail
        return super().minimum_to_decode(want, avail)
