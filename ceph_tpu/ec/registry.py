"""Erasure-code plugin registry.

Mirrors the reference's dlopen-based registry semantics
(ref: src/erasure-code/ErasureCodePlugin.cc ErasureCodePluginRegistry:
singleton, ``factory(plugin_name, profile) -> ErasureCodeInterfaceRef``,
load-once caching) with in-process registration instead of dlopen.

``jerasure`` and ``isa`` are registered as compatibility aliases resolving to
the JAX backend with the matching default technique, so reference benchmark
invocations (``--plugin jerasure``) run unmodified.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, Mapping

from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.ec.jax_plugin import ErasureCodeJax
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")


@functools.lru_cache(maxsize=1)
def _native_available() -> bool:
    """One build probe per process — factory() runs per EC instance and
    must not fork `make` every time."""
    try:
        from ceph_tpu.interop.native import build_native
        build_native()
        return True
    except (ImportError, RuntimeError):
        log.dout(1, "isa: native backend unavailable, "
                    "falling back to jax")
        return False


class ErasureCodePluginRegistry:
    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plugins: dict[str, Callable[[], ErasureCodeInterface]] = {}

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
                cls._instance._register_builtins()
            return cls._instance

    def _register_builtins(self) -> None:
        from ceph_tpu.ec.clay import ErasureCodeClay
        from ceph_tpu.ec.lrc import ErasureCodeLrc
        from ceph_tpu.ec.shec import ErasureCodeShec

        self.add("jax", ErasureCodeJax)
        # Compatibility alias: same techniques, same parity bytes.
        self.add("jerasure", ErasureCodeJax)
        # "isa" resolves dynamically (factory): the native C++ RS
        # backend when the toolchain can build it — see _isa_ctor.
        self.add("isa", ErasureCodeJax)
        self.add("lrc", ErasureCodeLrc)
        self.add("shec", ErasureCodeShec)
        self.add("clay", ErasureCodeClay)
        try:  # the build itself is lazy; only a missing module skips this
            from ceph_tpu.interop.native import ErasureCodeRef
        except ImportError:  # pragma: no cover
            pass
        else:
            self.add("ref", ErasureCodeRef)

    def add(self, name: str,
            ctor: Callable[[], ErasureCodeInterface]) -> None:
        """ref: ErasureCodePluginRegistry::add."""
        with self._lock:
            self._plugins[name] = ctor

    def load(self, name: str) -> Callable[[], ErasureCodeInterface]:
        """ref: ErasureCodePluginRegistry::load (dlopen analog)."""
        with self._lock:
            if name not in self._plugins:
                raise KeyError(
                    f"erasure-code plugin {name!r} not found; "
                    f"registered: {sorted(self._plugins)}")
            return self._plugins[name]

    def _isa_ctor(self, prof) -> tuple[type, bool]:
        """plugin=isa -> the INDEPENDENT native C++ RS backend, filling
        the role ISA-L plays upstream (the optimized CPU path distinct
        from jerasure) — so a jerasure<->isa parity cross-check compares
        two implementations, not one backend with two names (VERDICT r3
        weak #7). RS/Cauchy techniques only; anything else, or a missing
        toolchain, falls back to the JAX backend with
        ``independent=False`` so tests can skip the oracle honestly."""
        tech = prof.get("technique", "reed_sol_van")
        mapped = {"cauchy": "cauchy_good"}.get(tech, tech)
        if mapped in ("reed_sol_van", "cauchy_orig", "cauchy_good") \
                and _native_available():
            from ceph_tpu.interop.native import ErasureCodeRef
            prof["technique"] = mapped
            return ErasureCodeRef, True
        return ErasureCodeJax, False

    def factory(self, name: str,
                profile: Mapping[str, str] | str) -> ErasureCodeInterface:
        """ref: ErasureCodePluginRegistry::factory."""
        prof = ErasureCodeProfile.parse(profile)
        prof.setdefault("plugin", name)
        independent = None
        if name == "isa":
            self.load(name)              # keep not-found semantics
            ctor, independent = self._isa_ctor(prof)
        else:
            ctor = self.load(name)
        ec = ctor()
        ec.init(prof)
        if independent is not None:
            ec.independent = independent
        log.dout(5, "factory", plugin=name, profile=str(prof))
        return ec


def factory(profile: Mapping[str, str] | str) -> ErasureCodeInterface:
    """Build an EC backend from a profile carrying ``plugin=...``."""
    prof = ErasureCodeProfile.parse(profile)
    name = prof.get("plugin", "jax")
    return ErasureCodePluginRegistry.instance().factory(name, prof)
