"""The erasure-code contract and shared base behavior.

Mirrors the reference's stable plugin contract
(ref: src/erasure-code/ErasureCodeInterface.h ErasureCodeInterface) and the
shared base-class logic (ref: src/erasure-code/ErasureCode.cc ErasureCode):
profile parsing, chunk sizing/padding (encode_prepare), the default
minimum_to_decode, and byte-level encode/decode built on the subclass's
chunk-array kernels.

Byte-level methods (`encode`, `decode`, `decode_concat`) speak `bytes` for
harness compatibility; the TPU-native hot path is the array-level
`encode_chunks` / `decode_chunks` on (k, chunk) uint8 arrays, plus the
batched `encode_batch` used by the benchmark.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Iterable, Mapping, Sequence

import numpy as np

DEFAULT_ALIGNMENT = 128  # per-chunk byte alignment (TPU lane width)


class ErasureCodeProfile(dict):
    """An EC profile: ``plugin=jax technique=reed_sol_van k=8 m=3``.

    (ref: src/erasure-code/ErasureCodeInterface.h profile map;
    src/osd/OSDMap "erasure-code-profile" pool metadata.)
    """

    @classmethod
    def parse(cls, text: str | Mapping[str, str]) -> "ErasureCodeProfile":
        if isinstance(text, Mapping):
            return cls(text)
        prof = cls()
        # commas separate pairs only at bracket depth 0 (lrc layers carry
        # JSON values with their own commas)
        depth = 0
        parts: list[str] = [""]
        for ch in text:
            if ch in "[{":
                depth += 1
            elif ch in "]}":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append("")
            else:
                parts[-1] += ch
        for part in parts:
            for tok in part.split():
                key, _, val = tok.partition("=")
                prof[key.strip()] = val.strip()
        return prof

    def get_int(self, key: str, default: int) -> int:
        return int(self.get(key, default))

    def __str__(self) -> str:
        return " ".join(f"{k}={v}" for k, v in sorted(self.items()))


class ErasureCodeInterface(ABC):
    """ref: src/erasure-code/ErasureCodeInterface.h (same method surface)."""

    def __init__(self) -> None:
        self.profile = ErasureCodeProfile()
        self.k = 0
        self.m = 0

    # -- lifecycle --------------------------------------------------------
    @abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Parse the profile and build per-profile state."""

    # -- geometry ---------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_alignment(self) -> int:
        return DEFAULT_ALIGNMENT

    def get_chunk_size(self, object_size: int) -> int:
        """Bytes per chunk for an object of `object_size` bytes.

        round_up(object_size / k, alignment)
        (ref: src/erasure-code/jerasure/ErasureCodeJerasure.cc get_chunk_size).
        """
        align = self.get_alignment()
        chunk = -(-object_size // self.k)
        return -(-chunk // align) * align

    def get_chunk_mapping(self) -> list[int]:
        """chunk index -> shard remap; empty = identity
        (ref: ErasureCodeInterface.h get_chunk_mapping)."""
        return []

    # -- decode planning --------------------------------------------------
    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> set[int]:
        """Smallest chunk set needed to produce `want_to_read`.

        Base semantics (ref: src/erasure-code/ErasureCode.cc
        _minimum_to_decode): if everything wanted is available return it,
        else any k available chunks (ordered).
        """
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        if len(avail) < self.k:
            raise ValueError(
                f"cannot decode: {len(avail)} chunks available, need {self.k}")
        return set(sorted(avail)[:self.k])

    def minimum_to_decode_with_cost(
            self, want_to_read: Iterable[int],
            available: Mapping[int, int]) -> set[int]:
        """Like minimum_to_decode but `available` maps chunk -> read cost;
        prefer the cheapest k (ref: ErasureCodeInterface.h
        minimum_to_decode_with_cost)."""
        want = set(want_to_read)
        if want <= set(available):
            return want
        by_cost = sorted(available, key=lambda c: (available[c], c))
        if len(by_cost) < self.k:
            raise ValueError("not enough chunks to decode")
        return set(by_cost[:self.k])

    # -- array-level kernels (subclass provides) --------------------------
    @abstractmethod
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """(k, C) uint8 data chunks -> (m, C) uint8 parity chunks."""

    @abstractmethod
    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Reconstruct chunk ids `want` from available `chunks`."""

    def is_mds(self) -> bool:
        """True when any k chunks decode everything (RS); layered codes
        (lrc/shec/clay) override to False and may want more chunks."""
        return False

    # -- batched kernels (subclasses override with fused device paths) ----
    def encode_batch(self, data):
        """(B, k, C) uint8 -> (B, m, C) parity. Base: per-stripe loop."""
        data = np.asarray(data)
        return np.stack([np.asarray(self.encode_chunks(data[b]))
                         for b in range(data.shape[0])])

    def encode_batch_reference(self, data):
        """(B, k, C) uint8 -> (B, m, C) parity via a HOST-ONLY path —
        no jit, no device, bit-exact with ``encode_batch`` by
        construction. This is the last rung of the OSD aggregator's
        degrade ladder (osd/ec_aggregator): when the device encode
        keeps failing, a client write is served from here rather than
        erroring. Base: the per-stripe loop (still host-only when
        ``encode_chunks`` is — device plugins MUST override with a
        genuinely device-free implementation)."""
        data = np.asarray(data)
        return np.stack([np.asarray(self.encode_chunks(data[b]))
                         for b in range(data.shape[0])])

    def encode_batch_with_crc(self, data):
        """(B, k, C) -> (parity (B, m, C), row_crcs (B, k+m) | None).

        ``row_crcs`` are per-row raw CRC32 values (ec.crc) for every
        data AND parity row of the batch, produced in the SAME device
        program as the encode when the plugin supports fusion. Base
        plugins return None — callers fall back to host zlib.crc32
        (the ec.crc.hcrc_attr contract)."""
        return self.encode_batch(data), None

    def decode_batch(self, want: Sequence[int], avail: Sequence[int],
                     chunks):
        """(B, len(avail), C) -> (B, len(want), C). Base: per-stripe."""
        chunks = np.asarray(chunks)
        out = []
        for b in range(chunks.shape[0]):
            got = self.decode_chunks(
                list(want), {a: chunks[b, i] for i, a in enumerate(avail)})
            out.append(np.stack([np.asarray(got[w]) for w in want]))
        return np.stack(out)

    def decode_batch_reference(self, want: Sequence[int],
                               avail: Sequence[int], chunks):
        """(B, len(avail), C) -> (B, len(want), C) via a HOST-ONLY
        path — no jit, no device, bit-exact with ``decode_batch`` by
        construction. The last rung of the OSD read aggregator's
        degrade ladder (osd/ec_read_aggregator): when the device
        decode keeps failing, a degraded read is served from here
        rather than erroring. Base: the per-stripe loop (still
        host-only when ``decode_chunks`` is — device plugins MUST
        override with a genuinely device-free implementation)."""
        chunks = np.asarray(chunks)
        out = []
        for b in range(chunks.shape[0]):
            got = self.decode_chunks(
                list(want), {a: chunks[b, i] for i, a in enumerate(avail)})
            out.append(np.stack([np.asarray(got[w]) for w in want]))
        return np.stack(out)

    # -- byte-level API (base implements; harness-compatible) -------------
    def encode_prepare(self, data: bytes) -> np.ndarray:
        """Pad to k*chunk_size and carve into the (k, C) chunk array
        (ref: src/erasure-code/ErasureCode.cc encode_prepare)."""
        chunk = self.get_chunk_size(len(data))
        padded = np.zeros(self.k * chunk, dtype=np.uint8)
        padded[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        return padded.reshape(self.k, chunk)

    def encode(self, want_to_encode: Iterable[int],
               data: bytes) -> dict[int, bytes]:
        """ref: src/erasure-code/ErasureCode.cc encode."""
        chunks = self.encode_prepare(data)
        parity = np.asarray(self.encode_chunks(chunks))
        out: dict[int, bytes] = {}
        for i in want_to_encode:
            if i < self.k:
                out[i] = chunks[i].tobytes()
            else:
                out[i] = parity[i - self.k].tobytes()
        return out

    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, bytes],
               chunk_size: int | None = None) -> dict[int, bytes]:
        """ref: src/erasure-code/ErasureCode.cc decode -> decode_chunks."""
        arrs = {i: np.frombuffer(c, dtype=np.uint8) for i, c in chunks.items()}
        sizes = {a.shape[0] for a in arrs.values()}
        if chunk_size is not None:
            sizes.add(chunk_size)
        if len(sizes) > 1:
            raise ValueError(f"chunk size mismatch: {sorted(sizes)}")
        want = list(want_to_read)
        have = {i: arrs[i] for i in want if i in arrs}
        missing = [i for i in want if i not in arrs]
        if missing:
            have.update(self.decode_chunks(missing, arrs))
        return {i: np.asarray(have[i]).tobytes() for i in want}

    def decode_concat(self, chunks: Mapping[int, bytes]) -> bytes:
        """Reassemble the original object from data chunks
        (ref: src/erasure-code/ErasureCode.cc decode_concat)."""
        want = list(range(self.k))
        decoded = self.decode(want, chunks)
        return b"".join(decoded[i] for i in want)
