"""Locally Repairable Codes — layered erasure coding.

ref: src/erasure-code/lrc/ErasureCodeLrc.{h,cc}. An LRC profile is a
global ``mapping`` string over chunk positions (``D`` = original data,
``_`` = parity) plus ordered ``layers``: each layer is a sub-code over a
subset of positions (``D`` = layer input, ``c`` = layer output, ``_`` =
not in layer). Local layers make single-chunk repair read only the local
group (l chunks) instead of k — that is the whole point of the plugin.

The ``k/m/l`` shorthand generates the documented layout (ref:
doc/rados/operations/erasure-code-lrc.rst): (k+m)/l groups, each group =
one local parity followed by its share of global parities and data.

Layer kernels are the JAX RS backend, so batched encode remains a stack
of MXU matmuls (one per layer).

Provenance: the reference tree was empty during the survey (SURVEY.md
warning), so layer-generation parity with upstream is asserted from the
documented examples, pending byte-level verification.
"""

from __future__ import annotations

import json
from typing import Iterable, Mapping, Sequence

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.ec.jax_plugin import ErasureCodeJax
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")

DEFAULT_LAYER_PLUGIN = "technique=reed_sol_van"


class _Layer:
    """One sub-code: positions + a jax RS kernel sized to the layer."""

    def __init__(self, mapping: str, config: str):
        self.mapping = mapping
        self.data_pos = [i for i, ch in enumerate(mapping) if ch == "D"]
        self.coding_pos = [i for i, ch in enumerate(mapping) if ch == "c"]
        self.positions = sorted(self.data_pos + self.coding_pos)
        prof = ErasureCodeProfile.parse(config or DEFAULT_LAYER_PLUGIN)
        prof["k"] = str(len(self.data_pos))
        prof["m"] = str(len(self.coding_pos))
        prof.setdefault("technique", "reed_sol_van")
        self.code = ErasureCodeJax(prof)

    @property
    def k(self) -> int:
        return len(self.data_pos)

    @property
    def m(self) -> int:
        return len(self.coding_pos)


def generate_kml(k: int, m: int, l: int) -> tuple[str, list[list[str]]]:
    """k/m/l -> (mapping, layers) per the documented layout
    (ref: ErasureCodeLrc::parse_kml).

    (k+m) must be a multiple of l; each group of l global chunks gets one
    local parity, so chunk count = k + m + (k+m)/l. Within a group the
    order is [local][globals], globals being parities-first then data —
    reproducing the doc example k=4 m=2 l=3 ->
    mapping ``__DD__DD``, layers ``_cDD_cDD`` + one local ``c`` per group.
    """
    if (k + m) % l:
        raise ValueError(f"k+m={k + m} must be a multiple of l={l}")
    ngroups = (k + m) // l
    if m % ngroups:
        raise ValueError(f"m={m} must spread evenly over {ngroups} groups")
    per_group_m = m // ngroups
    n = (l + 1) * ngroups
    mapping: list[str] = []
    global_layer: list[str] = []
    for _ in range(ngroups):
        mapping.append("_")          # local parity slot
        global_layer.append("_")
        for s in range(l):           # the group's l global chunks
            is_parity = s < per_group_m
            mapping.append("_" if is_parity else "D")
            global_layer.append("c" if is_parity else "D")
    layers = [["".join(global_layer), ""]]
    for g in range(ngroups):
        row = ["_"] * n
        lo = g * (l + 1)
        row[lo] = "c"
        row[lo + 1:lo + 1 + l] = "D" * l
        layers.append(["".join(row), ""])
    return "".join(mapping), layers


class ErasureCodeLrc(ErasureCodeInterface):
    """plugin=lrc  (k=K m=M l=L | mapping=... layers=[[..],..])"""

    def __init__(self, profile: ErasureCodeProfile | str | None = None):
        super().__init__()
        self.mapping = ""
        self.layers: list[_Layer] = []
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        if "mapping" in profile:
            mapping = profile["mapping"]
            layers_spec = profile.get("layers", "[]")
            if isinstance(layers_spec, str):
                layers_spec = json.loads(layers_spec)
        else:
            k = profile.get_int("k", 4)
            m = profile.get_int("m", 2)
            l = profile.get_int("l", 3)
            mapping, layers_spec = generate_kml(k, m, l)
        self.mapping = mapping
        self.layers = [_Layer(lm, cfg) for lm, cfg in layers_spec]
        self.k = mapping.count("D")
        self.m = len(mapping) - self.k
        for layer in self.layers:
            if len(layer.mapping) != len(mapping):
                raise ValueError(
                    f"layer {layer.mapping!r} length != mapping "
                    f"{mapping!r}")
        log.dout(5, "lrc init", mapping=mapping,
                 layers=[la.mapping for la in self.layers])

    # -- geometry ---------------------------------------------------------
    def get_chunk_count(self) -> int:
        return len(self.mapping)

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_chunk_mapping(self) -> list[int]:
        """chunk id -> mapping position: ids 0..k-1 are the D positions in
        order, ids k.. are the parity positions in order
        (ref: ErasureCodeInterface.h get_chunk_mapping)."""
        dpos = [i for i, ch in enumerate(self.mapping) if ch == "D"]
        ppos = [i for i, ch in enumerate(self.mapping) if ch != "D"]
        return dpos + ppos

    def _pos_of(self) -> list[int]:
        return self.get_chunk_mapping()

    def _id_of(self) -> dict[int, int]:
        return {p: i for i, p in enumerate(self.get_chunk_mapping())}

    # -- encode -----------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """(k, C) data -> (n-k, C) parity, in non-D position order.

        Single-stripe view of encode_batch (one algorithm, one code path).
        """
        return np.asarray(self.encode_batch(np.asarray(data)[None])[0])

    def encode_batch(self, data):
        """(B, k, C) -> (B, m, C): each layer is one batched device
        matmul over the stripe batch (stays on device between layers)."""
        import jax.numpy as jnp
        data = jnp.asarray(data, dtype=jnp.uint8)
        B, _, C = data.shape
        n = len(self.mapping)
        chunks = jnp.zeros((B, n, C), dtype=jnp.uint8)
        dpos = jnp.asarray(
            [i for i, ch in enumerate(self.mapping) if ch == "D"])
        chunks = chunks.at[:, dpos, :].set(data)
        for layer in self.layers:
            parity = layer.code.encode_batch(
                chunks[:, jnp.asarray(layer.data_pos), :])
            chunks = chunks.at[:, jnp.asarray(layer.coding_pos), :].set(
                parity)
        ppos = jnp.asarray(
            [i for i, ch in enumerate(self.mapping) if ch != "D"])
        return chunks[:, ppos, :]

    def _position_chunks(self, chunks: Mapping[int, np.ndarray],
                         C: int) -> tuple[np.ndarray, set[int]]:
        n = len(self.mapping)
        arr = np.zeros((n, C), dtype=np.uint8)
        have = set()
        for i, c in chunks.items():
            arr[i] = c
            have.add(i)
        return arr, have

    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Iterative layer repair (ref: ErasureCodeLrc::decode): sweep
        layers, decoding any layer whose erasures are recoverable, until
        the wanted chunks exist or no layer makes progress.

        `want`/`chunks` use chunk ids (data-first); internally everything
        is positional via get_chunk_mapping."""
        pos_of = self._pos_of()
        id_of = self._id_of()
        pchunks = {pos_of[i]: c for i, c in chunks.items()}
        out = self._decode_positions([pos_of[i] for i in want], pchunks)
        return {id_of[p]: v for p, v in out.items()}

    def _decode_positions(self, want: Sequence[int],
                          chunks: Mapping[int, np.ndarray]
                          ) -> dict[int, np.ndarray]:
        C = next(iter(chunks.values())).shape[0]
        arr, have = self._position_chunks(chunks, C)
        want_set = set(want)
        for _ in range(len(self.layers) + 1):
            if want_set <= have:
                break
            progress = False
            for layer in self.layers:
                missing = [p for p in layer.positions if p not in have]
                if not missing:
                    continue
                avail = [p for p in layer.positions if p in have]
                if len(avail) < layer.k:
                    continue
                # layer-local ids
                local_id = {p: j for j, p in enumerate(
                    layer.data_pos + layer.coding_pos)}
                sub = {local_id[p]: arr[p] for p in avail}
                out = layer.code.decode_chunks(
                    [local_id[p] for p in missing], sub)
                for p in missing:
                    arr[p] = out[local_id[p]]
                    have.add(p)
                progress = True
            if not progress:
                break
        if not want_set <= have:
            raise ValueError(
                f"cannot decode {sorted(want_set - have)} from "
                f"{sorted(chunks)}")
        return {p: arr[p] for p in want}

    # -- repair planning --------------------------------------------------
    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> set[int]:
        """Cheapest chunk set: prefer a single layer that covers the
        erasures (local repair), else simulate the iterative decode
        (ref: ErasureCodeLrc::_minimum_to_decode layer walk).

        Speaks chunk ids; positional internally."""
        pos_of = self._pos_of()
        id_of = self._id_of()
        out = self._minimum_positions(
            {pos_of[i] for i in want_to_read},
            {pos_of[i] for i in available})
        return {id_of[p] for p in out}

    def _minimum_positions(self, want_to_read: Iterable[int],
                           available: Iterable[int]) -> set[int]:
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        missing = want - avail
        best: set[int] | None = None
        for layer in self.layers:
            pos = set(layer.positions)
            if not missing <= pos:
                continue
            layer_avail = sorted(pos & avail)
            if len(layer_avail) < layer.k:
                continue
            cand = set(layer_avail[:layer.k]) | (want & avail)
            if best is None or len(cand) < len(best):
                best = cand
        if best is not None:
            return best
        # multi-layer repair: simulate, tracking consumed reads
        have = set(avail)
        used: set[int] = set(want & avail)
        for _ in range(len(self.layers) + 1):
            if want <= have:
                break
            progress = False
            for layer in self.layers:
                pos = layer.positions
                miss = [p for p in pos if p not in have]
                la = [p for p in pos if p in have]
                if not miss or len(la) < layer.k:
                    continue
                used |= set(la[:layer.k]) & avail
                have |= set(miss)
                progress = True
            if not progress:
                break
        if not want <= have:
            raise ValueError(f"cannot decode {sorted(want - have)}")
        return used
