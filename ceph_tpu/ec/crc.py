"""CRC32 as GF(2) linear algebra — the fused checksum+encode plan.

The EC write path stamps every shard with a write-time ``_hcrc``
(zlib.crc32 of the shard bytes; the hinfo analog scrub-repair uses to
LOCATE a corrupt shard). Historically that was three separate host-side
``zlib.crc32`` sites in ``osd/ec_pg.py``; this module lets the checksum
ride the SAME device program as the encode, so checksum+encode is one
kernel launch per stripe batch.

The decomposition (all facts pinned by tests/test_ec_agg.py):

- ``raw(m) = zlib.crc32(m, 0xffffffff) ^ 0xffffffff`` is the init-free
  CRC state machine. It is **linear over GF(2)** in the message bits
  (``raw(a ^ b) = raw(a) ^ raw(b)`` for equal lengths), and
  ``zlib.crc32(m) = raw(m) ^ zlib.crc32(b"\\0" * len(m))`` — the
  init/final-xor affine part depends only on the length.
- For a fixed row length C, ``raw`` of one row is a (32 x 8C) GF(2)
  matrix ``G_C`` applied to the row's bits: ON DEVICE this is one int8
  matmul per stripe batch (``(rows, 8C) @ (8C, 32) mod 2``), landing on
  the MXU right next to the encode matmul — the fused pass emits a
  uint32 row-CRC per shard row of the batch (data AND parity rows).
- Rows concatenate through the fixed 32x32 "append C zero bytes"
  operator ``M_C``: ``raw(A || B) = M_C(raw(A)) ^ raw(B)``. The
  per-shard fold over a write's ``count`` rows is O(count) 32-bit host
  ops on the device-produced row CRCs (vectorized across shards) — the
  O(bytes) work stays on device, in the encode program.

Everything here is host-side plan construction (numpy + zlib), cached
per chunk size, exactly like the bit-matrix expansion in gf/tables.py.
"""

from __future__ import annotations

import functools
import zlib

import numpy as np

_M32 = 0xFFFFFFFF


def raw_crc(data: bytes, state: int = 0) -> int:
    """The init-free CRC32 state machine (zlib pre/post-inverts
    internally; this peels that off). Linear over GF(2) in the message
    bits at state 0; composes: ``raw(a + b) = raw(b, raw(a))``."""
    return zlib.crc32(data, state ^ _M32) ^ _M32


@functools.lru_cache(maxsize=1)
def _byte_table() -> np.ndarray:
    """(256,) uint64: raw CRC of each single-byte message."""
    return np.array([raw_crc(bytes([x])) for x in range(256)],
                    dtype=np.uint64)


def _zero_byte_update(state: np.ndarray) -> np.ndarray:
    """Advance raw CRC state(s) by one zero message byte (vectorized)."""
    t = _byte_table()
    s = np.asarray(state, dtype=np.uint64)
    return (s >> np.uint64(8)) ^ t[(s & np.uint64(0xFF)).astype(np.int64)]


@functools.lru_cache(maxsize=8)
def row_crc_matrix(chunk_size: int) -> np.ndarray:
    """(8C, 32) int8 GF(2) matrix: bits of a C-byte row (LSB-first per
    byte, matching gf.ops.unpack_bits) -> bits of the row's raw CRC.

    Row 8p+b is the 32-bit contribution of byte position p, bit b —
    built by walking the single-byte table backward through the
    zero-byte-append operator (position p is followed by C-1-p zero
    bytes in the row's state machine)."""
    C = int(chunk_size)
    contrib = np.zeros((C, 8), dtype=np.uint64)
    contrib[C - 1] = _byte_table()[[1 << b for b in range(8)]]
    for p in range(C - 2, -1, -1):
        contrib[p] = _zero_byte_update(contrib[p + 1])
    bits = (contrib[:, :, None] >> np.arange(32, dtype=np.uint64)) \
        & np.uint64(1)
    return bits.reshape(8 * C, 32).astype(np.int8)


_DEVICE_ROW_CRC_CACHE: dict[int, object] = {}


def device_row_crcs(rows: np.ndarray) -> np.ndarray:
    """ONE batched device CRC job: (R, C) uint8 rows -> (R,) uint32
    raw row CRCs.

    The standalone twin of the fused encode+crc pass — same 8-bit-plane
    GF(2) matmul against ``row_crc_matrix(C)`` (plane b multiplies
    ``G[b::8]``), jitted once per chunk size and accounted through
    devmon as ``scrub_crc``. Deep scrub uses it to turn a whole
    chunk-map sweep's per-object ``zlib.crc32`` calls into O(batches)
    device launches; the per-shard fold back to zlib-equal values is
    :func:`shard_crc32` (O(rows) host work)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.utils.devmon import devmon as _devmon

    arr = np.ascontiguousarray(rows, dtype=np.uint8)
    if arr.ndim != 2:
        raise ValueError("device_row_crcs wants a (rows, C) batch")
    C = int(arr.shape[1])
    # pow2-pad the row axis (same discipline as the EC aggregators):
    # scrub batches arrive at arbitrary per-PG row counts, and an
    # unpadded launch would compile one program per count — padding
    # bounds the jit cache at O(log max_rows) shapes per chunk size
    R = int(arr.shape[0])
    padded = 1 << (R - 1).bit_length() if R > 1 else 1
    if padded != R:
        arr = np.concatenate(
            [arr, np.zeros((padded - R, C), dtype=np.uint8)])
    fn = _DEVICE_ROW_CRC_CACHE.get(C)
    if fn is None:
        G = jnp.asarray(row_crc_matrix(C))                # (8C, 32) i8

        def _kern(d):
            # bit-plane at a time keeps the matmul operand at
            # batch-bytes size (the naive 8C bit expansion is 8x)
            acc = jnp.zeros((d.shape[0], 32), dtype=jnp.int32)
            for b in range(8):
                plane = ((d >> jnp.uint8(b)) &
                         jnp.uint8(1)).astype(jnp.int8)
                acc = acc + jnp.matmul(
                    plane, G[b::8, :],
                    preferred_element_type=jnp.int32)
            bit32 = (acc & 1).astype(jnp.uint32)
            weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
            return jnp.sum(bit32 * weights[None, :], axis=1,
                           dtype=jnp.uint32)

        fn = _DEVICE_ROW_CRC_CACHE[C] = jax.jit(_kern)
    out = _devmon().jit_call("scrub_crc", (C, tuple(arr.shape)),
                             fn, arr)
    return np.asarray(out)[:R]


@functools.lru_cache(maxsize=8)
def _shift_columns(chunk_size: int) -> np.ndarray:
    """(32,) uint32-valued columns of M_C, the 'append C zero bytes'
    operator on raw CRC states: column j = M_C applied to basis 2^j."""
    cols = np.array([1 << j for j in range(32)], dtype=np.uint64)
    for _ in range(int(chunk_size)):
        cols = _zero_byte_update(cols)
    return cols


def combine_row_crcs(row_crcs: np.ndarray, chunk_size: int) -> np.ndarray:
    """Fold per-row raw CRCs into per-shard raw CRCs.

    ``row_crcs``: (..., count) uint32 — count C-byte rows per shard, in
    concatenation order. Returns (...) uint64-valued raw CRC of each
    shard's count*C bytes. O(count) vectorized 32-bit host ops — the
    O(bytes) part already ran on device."""
    rc = np.asarray(row_crcs, dtype=np.uint64)
    cols = _shift_columns(chunk_size)
    state = np.zeros(rc.shape[:-1], dtype=np.uint64)
    j = np.arange(32, dtype=np.uint64)
    for i in range(rc.shape[-1]):
        bits = ((state[..., None] >> j) & np.uint64(1)).astype(bool)
        state = np.bitwise_xor.reduce(
            np.where(bits, cols, np.uint64(0)), axis=-1) ^ rc[..., i]
    return state


def _apply_cols(cols: np.ndarray, state: int) -> int:
    """Apply a 32x32 GF(2) operator (given as its 32 basis-column
    images) to one state."""
    j = np.arange(32, dtype=np.uint64)
    bits = ((np.uint64(state) >> j) & np.uint64(1)).astype(bool)
    return int(np.bitwise_xor.reduce(
        np.where(bits, cols, np.uint64(0))))


@functools.lru_cache(maxsize=64)
def _zero_crc(length: int) -> int:
    """zlib.crc32 of `length` zero bytes — the affine (init/final-xor)
    part of the checksum, a function of the length alone. Computed in
    O(log length) by square-and-multiply over the append-one-zero-byte
    operator (ref: crc32_combine) — materializing a length-sized zero
    buffer here would re-introduce the O(bytes) host work the fused
    path exists to offload."""
    state = _M32            # the pre-inverted init register
    cols = _zero_byte_update(
        np.array([1 << j for j in range(32)], dtype=np.uint64))
    n = int(length)
    while n:
        if n & 1:
            state = _apply_cols(cols, state)
        n >>= 1
        if n:
            # square the operator: image of basis j under cols∘cols
            cols = np.array([_apply_cols(cols, int(c)) for c in cols],
                            dtype=np.uint64)
    return state ^ _M32


def shard_crc32(row_crcs: np.ndarray, chunk_size: int) -> np.ndarray:
    """Device-produced row CRCs -> zlib.crc32-equal per-shard values.

    ``row_crcs``: (..., count) uint32 from the fused pass. Returns
    (...) values equal to ``zlib.crc32`` of each shard's bytes."""
    rc = np.asarray(row_crcs, dtype=np.uint64)
    lin = combine_row_crcs(rc, chunk_size)
    return lin ^ np.uint64(_zero_crc(rc.shape[-1] * int(chunk_size)))


def hcrc_attr(shard_bytes: bytes, row_crcs=None,
              chunk_size: int | None = None) -> bytes:
    """The ONE producer of the ``_hcrc`` shard attribute (4 bytes LE).

    Consumes the fused kernel's per-row CRC output when the caller has
    one (``row_crcs``: (count,) uint32 for this shard, ``chunk_size``
    required), and falls back to host-side ``zlib.crc32`` otherwise —
    both producers are pinned byte-for-byte equal by test."""
    if row_crcs is not None:
        if not chunk_size:
            raise ValueError(
                "row_crcs needs the chunk size to combine")
        v = int(shard_crc32(np.asarray(row_crcs), chunk_size))
    else:
        v = zlib.crc32(shard_bytes)
    return int(v).to_bytes(4, "little")
