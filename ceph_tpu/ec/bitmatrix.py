"""Bit-matrix (array-code) constructions: liberation, blaum_roth,
liber8tion — jerasure's minimal-density RAID-6 family.

ref: src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}
(ErasureCodeJerasureLiberation / BlaumRoth / Liber8tion) over the vendored
jerasure liberation.c / minimal-density codes from Plank's papers.

These are m=2 codes defined directly as (2w x kw) binary matrices acting
on w "packets" per chunk (bit-planes at packet granularity, not byte
granularity). On the reference's CPU path their selling point is
XOR-schedule minimality; on the MXU the whole bitmatrix is one binary
matmul, so density is irrelevant to speed — but the codes themselves (and
their w-packet chunk geometry) are implemented faithfully:

- blaum_roth: w with w+1 prime. Q-block for drive i is the matrix of
  multiplication by x^i in the ring GF(2)[x]/(1+x+...+x^w) — the
  published Blaum-Roth construction.
- liberation: w prime, k <= w. Q-block for drive i is the cyclic shift
  sigma^i plus one extra bit (the paper's minimal-density trick); the
  extra-bit position follows the paper's formula and every construction
  is verified MDS at build time (all 1- and 2-erasure patterns), with a
  deterministic search fallback should the formula position fail.
- liber8tion: the w=8 member of the same family.

Byte-compatibility with jerasure's shipped tables could not be verified
(reference mount empty — SURVEY.md provenance warning); the constructions
are MDS-verified against their published definitions instead.
"""

from __future__ import annotations

import functools

import numpy as np


# ---------------------------------------------------------------------------
# GF(2) linear algebra
# ---------------------------------------------------------------------------

def gf2_inv(a: np.ndarray) -> np.ndarray:
    """Inverse of a square 0/1 matrix over GF(2); raises if singular."""
    n = a.shape[0]
    work = np.concatenate([a.astype(np.uint8) & 1,
                           np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = None
        for row in range(col, n):
            if work[row, col]:
                piv = row
                break
        if piv is None:
            raise np.linalg.LinAlgError("singular over GF(2)")
        if piv != col:
            work[[col, piv]] = work[[piv, col]]
        for row in range(n):
            if row != col and work[row, col]:
                work[row] ^= work[col]
    return work[:, n:]


def gf2_rank(a: np.ndarray) -> int:
    work = (a.astype(np.uint8) & 1).copy()
    rank = 0
    rows, cols = work.shape
    for col in range(cols):
        piv = None
        for row in range(rank, rows):
            if work[row, col]:
                piv = row
                break
        if piv is None:
            continue
        work[[rank, piv]] = work[[piv, rank]]
        for row in range(rows):
            if row != rank and work[row, col]:
                work[row] ^= work[rank]
        rank += 1
        if rank == rows:
            break
    return rank


def is_mds(bitmatrix: np.ndarray, k: int, m: int, w: int) -> bool:
    """Every erasure of <= m of the k+m drives leaves full rank."""
    from itertools import combinations
    g = np.concatenate([np.eye(k * w, dtype=np.uint8),
                        bitmatrix.astype(np.uint8)], axis=0)
    drives = k + m
    rows_of = [list(range(d * w, (d + 1) * w)) for d in range(drives)]
    for r in range(1, m + 1):
        for erased in combinations(range(drives), r):
            keep = [i for d in range(drives) if d not in erased
                    for i in rows_of[d]]
            if gf2_rank(g[keep]) < k * w:
                return False
    return True


# ---------------------------------------------------------------------------
# Constructions
# ---------------------------------------------------------------------------

def _sigma(w: int, i: int) -> np.ndarray:
    """Cyclic shift matrix: ones at (r, (r + i) mod w)."""
    m = np.zeros((w, w), dtype=np.uint8)
    r = np.arange(w)
    m[r, (r + i) % w] = 1
    return m


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n ** 0.5) + 1):
        if n % p == 0:
            return False
    return True


@functools.lru_cache(maxsize=None)
def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw): P row-block all-identity; Q-block i = mult-by-x^i in
    GF(2)[x]/(1 + x + ... + x^w) (requires w+1 prime, k <= w)."""
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth requires w+1 prime (w={w})")
    if not (1 <= k <= w):
        raise ValueError(f"blaum_roth requires k <= w (k={k}, w={w})")
    # multiplication-by-x matrix on basis (1, x, .., x^(w-1)):
    # x * x^j = x^(j+1); x^w = 1 + x + ... + x^(w-1)  (char 2, M_p = 0)
    mx = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        mx[j + 1, j] = 1
    mx[:, w - 1] = 1
    blocks_p = [np.eye(w, dtype=np.uint8) for _ in range(k)]
    xi = np.eye(w, dtype=np.uint8)
    blocks_q = []
    for i in range(k):
        blocks_q.append(xi.copy())
        xi = (mx @ xi) & 1
    out = np.concatenate([np.concatenate(blocks_p, axis=1),
                          np.concatenate(blocks_q, axis=1)], axis=0)
    assert is_mds(out, k, 2, w), "blaum_roth construction not MDS"
    return out


@functools.lru_cache(maxsize=None)
def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw) liberation code: Q-block i = sigma^i plus one extra bit
    (minimal density, w+1 ones per block for i > 0). The extra-bit
    position starts from the paper's formula and is search-adjusted until
    the whole code verifies MDS (deterministic, cached)."""
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w (w={w})")
    if not (1 <= k <= w):
        raise ValueError(f"liberation requires k <= w (k={k}, w={w})")
    blocks_q = [np.eye(w, dtype=np.uint8)]
    for i in range(1, k):
        placed = None
        # paper formula first, then deterministic search
        y = (i * (w - 1) // 2) % w
        candidates = [(y, (y + i - 1) % w)] + [
            (r, c) for r in range(w) for c in range(w)]
        for r, c in candidates:
            blk = _sigma(w, i)
            if blk[r, c]:
                continue
            blk[r, c] = 1
            trial = blocks_q + [blk]
            if _pairwise_invertible(trial, w):
                placed = blk
                break
        if placed is None:
            raise ValueError(f"no liberation extra-bit found (k={k} w={w})")
        blocks_q.append(placed)
    out = np.concatenate(
        [np.concatenate([np.eye(w, dtype=np.uint8)] * k, axis=1),
         np.concatenate(blocks_q, axis=1)], axis=0)
    assert is_mds(out, k, 2, w), "liberation construction not MDS"
    return out


def _pairwise_invertible(blocks: list[np.ndarray], w: int) -> bool:
    """MDS conditions for m=2 array codes with identity P-blocks:
    every Q-block invertible and every pairwise XOR invertible."""
    for i, bi in enumerate(blocks):
        if gf2_rank(bi) < w:
            return False
        for bj in blocks[:i]:
            if gf2_rank(bi ^ bj) < w:
                return False
    return True


@functools.lru_cache(maxsize=None)
def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """The w=8 member (ref: ErasureCodeJerasureLiber8tion; w=8 is not
    prime, so the extra-bit search carries the construction)."""
    w = 8
    if not (1 <= k <= w):
        raise ValueError(f"liber8tion requires k <= 8 (k={k})")
    # w=8 is even, so sigma^i + sigma^j can be singular; the paper's w=8
    # flats carry up to two extra bits, and greedy per-drive choices can
    # dead-end — deterministic backtracking over 1- then 2-extra-bit
    # candidates per drive, with blocks bit-packed as row-integers so the
    # GF(2) invertibility checks are integer elimination.
    from itertools import combinations

    def pack(blk) -> tuple[int, ...]:
        return tuple(int("".join(str(int(b)) for b in row[::-1]), 2)
                     for row in blk)

    def inv_rows(rows) -> bool:
        rows = list(rows)
        for col in range(w):
            bit = 1 << col
            piv = next((ri for ri in range(col, w) if rows[ri] & bit), None)
            if piv is None:
                return False
            rows[col], rows[piv] = rows[piv], rows[col]
            for ri in range(w):
                if ri != col and rows[ri] & bit:
                    rows[ri] ^= rows[col]
        return True

    def candidates(i):
        base = pack(_sigma(w, i))
        cells = [(r, 1 << c) for r in range(w) for c in range(w)]
        for n_extra in (1, 2):
            for extra in combinations(cells, n_extra):
                rows = list(base)
                ok = True
                for r, bit in extra:
                    if rows[r] & bit:
                        ok = False
                        break
                    rows[r] |= bit
                if ok and inv_rows(rows):
                    yield tuple(rows)

    budget = [200_000]          # pairwise-check budget before fallback

    def search(blocks, i):
        if i == k:
            return blocks
        for blk in candidates(i):
            budget[0] -= len(blocks)
            if budget[0] < 0:
                return None
            if all(inv_rows([a ^ b for a, b in zip(blk, prev)])
                   for prev in blocks):
                got = search(blocks + [blk], i + 1)
                if got is not None:
                    return got
        return None

    packed = search([pack(np.eye(w, dtype=np.uint8))], 1)
    if packed is not None:
        blocks_q = []
        for rows in packed:
            blk = np.zeros((w, w), dtype=np.uint8)
            for r, bits in enumerate(rows):
                for c in range(w):
                    blk[r, c] = (bits >> c) & 1
            blocks_q.append(blk)
    else:
        # Search budget exhausted: fall back to GF(256) companion-power
        # blocks X_i = bitmatrix(2^i) — always MDS (2^i are distinct
        # nonzero field elements), denser than the paper's flats; the
        # XOR-density difference is irrelevant on the MXU and byte-compat
        # with jerasure's shipped tables is unverifiable regardless
        # (reference mount empty).
        from ceph_tpu.gf import tables as gft
        acc = 1
        blocks_q = []
        for _ in range(k):
            blocks_q.append(
                gft.expand_bitmatrix(
                    np.asarray([[acc]], dtype=np.uint8)).astype(np.uint8))
            acc = gft.gf_mul(acc, 2)
    out = np.concatenate(
        [np.concatenate([np.eye(w, dtype=np.uint8)] * k, axis=1),
         np.concatenate(blocks_q, axis=1)], axis=0)
    assert is_mds(out, k, 2, w), "liber8tion construction not MDS"
    return out


# default word sizes per technique (ref: ErasureCodeJerasure.cc
# DEFAULT_W per subclass)
def default_w(technique: str, k: int) -> int:
    if technique == "liber8tion":
        return 8
    if technique == "liberation":
        w = max(k, 3)
        while not _is_prime(w):
            w += 1
        return w
    if technique == "blaum_roth":
        w = max(k, 4)
        while not _is_prime(w + 1):
            w += 1
        return w
    raise ValueError(technique)


def bitmatrix_for(technique: str, k: int, m: int, w: int) -> np.ndarray:
    if m != 2:
        raise ValueError(f"{technique} is a RAID-6 code: m must be 2, "
                         f"got {m}")
    if technique == "liberation":
        return liberation_bitmatrix(k, w)
    if technique == "blaum_roth":
        return blaum_roth_bitmatrix(k, w)
    if technique == "liber8tion":
        if w != 8:
            raise ValueError("liber8tion fixes w=8")
        return liber8tion_bitmatrix(k)
    raise ValueError(f"unknown bitmatrix technique {technique!r}")


def decode_bitmatrix(bitmatrix: np.ndarray, k: int, m: int, w: int,
                     available: tuple[int, ...],
                     want: tuple[int, ...]) -> np.ndarray:
    """(len(want)*w, len(available)*w) GF(2) matrix reconstructing the
    wanted drives' packets from the available drives' packets — the
    per-erasure-pattern inversion, bitmatrix flavor."""
    g = np.concatenate([np.eye(k * w, dtype=np.uint8),
                        bitmatrix.astype(np.uint8)], axis=0)
    avail = list(available)
    rows = [r for d in avail for r in range(d * w, (d + 1) * w)]
    sub = g[rows]                              # (len(avail)*w, kw)
    # solve sub @ data = chunks: pick kw independent rows
    # (gaussian elimination with row tracking)
    need = k * w
    work = sub.copy()
    chosen: list[int] = []
    cols_done = 0
    order = list(range(work.shape[0]))
    for col in range(need):
        piv = None
        for ri in range(cols_done, work.shape[0]):
            if work[ri, col]:
                piv = ri
                break
        if piv is None:
            raise np.linalg.LinAlgError("not decodable from available set")
        work[[cols_done, piv]] = work[[piv, cols_done]]
        order[cols_done], order[piv] = order[piv], order[cols_done]
        for ri in range(work.shape[0]):
            if ri != cols_done and work[ri, col]:
                work[ri] ^= work[cols_done]
        cols_done += 1
    chosen = order[:need]
    inv = gf2_inv(sub[chosen])                 # data = inv @ chunks[chosen]
    wanted_rows = [r for d in want for r in range(d * w, (d + 1) * w)]
    d = (g[wanted_rows].astype(np.int32) @ inv.astype(np.int32)) & 1
    out = np.zeros((len(want) * w, len(avail) * w), dtype=np.uint8)
    for j, src in enumerate(chosen):
        out[:, src] = d[:, j]
    return out
