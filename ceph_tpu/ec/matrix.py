"""Reed-Solomon coding-matrix constructions over GF(2^8).

Reimplements (from the published algorithms, not the code) the matrix
constructions used by the reference's jerasure and isa plugins so that parity
bytes are compatible:

- ``reed_sol_van``: systematic matrix derived from an extended Vandermonde
  matrix by Gauss-Jordan column elimination — Plank's construction
  (ref: src/erasure-code/jerasure vendored reed_sol.c
  reed_sol_vandermonde_coding_matrix / reed_sol_big_vandermonde_distribution_matrix).
- ``cauchy_orig``: C[i][j] = 1/(x_i + y_j) with x_i = i, y_j = m + j
  (ref: vendored cauchy.c cauchy_original_coding_matrix).
- ``cauchy_good``: cauchy_orig column-normalized so row 0 is all ones
  (ref: vendored cauchy.c cauchy_improve_coding_matrix; we apply the
  normalization step, not the bit-count row optimization, which only affects
  XOR-schedule cost, not the code itself).

NOTE (provenance): the reference tree was unavailable (SURVEY.md warning), so
bit-compatibility with jerasure is asserted from the published algorithm and
property-tested (systematic + MDS), pending byte-level verification against a
live reference build.
"""

from __future__ import annotations

import functools

import numpy as np

from ceph_tpu.gf import tables


def extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """Extended Vandermonde matrix, rows x cols over GF(2^8).

    Row 0 = e_0, row rows-1 = e_{cols-1}, row i (0<i<rows-1) = [i^j for j].
    MDS for rows <= 257 at w=8.
    """
    if rows > 256 + 1:
        raise ValueError("k+m must be <= 257 at w=8")
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    v[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i, j] = acc
            acc = tables.gf_mul(acc, i)
    return v


def _systematize(dist: np.ndarray, cols: int) -> np.ndarray:
    """Column-eliminate so the top cols x cols block is the identity.

    Mirrors the elimination order of the published jerasure construction:
    column operations pivoting down the diagonal, then normalize row `cols`
    to all ones via column scaling, then scale each remaining row so its
    first element is one.
    """
    rows = dist.shape[0]
    dist = dist.copy()
    for i in range(1, cols):
        # Pivot: find a row >= i with a nonzero in column i, swap into row i.
        if dist[i, i] == 0:
            for j in range(i + 1, rows):
                if dist[j, i]:
                    dist[[i, j]] = dist[[j, i]]
                    break
            else:
                raise ValueError("singular construction")
        # Scale column i so dist[i, i] == 1.
        if dist[i, i] != 1:
            inv = tables.gf_inv(int(dist[i, i]))
            dist[:, i] = tables.gf_mul_np(dist[:, i], inv)
        # Zero the rest of row i with column ops (col_j += e * col_i).
        for j in range(cols):
            e = int(dist[i, j])
            if j != i and e:
                dist[:, j] ^= tables.gf_mul_np(e, dist[:, i])
    if rows > cols:
        # Make row `cols` all ones by scaling columns.
        for j in range(cols):
            e = int(dist[cols, j])
            if e == 0:
                raise ValueError("singular construction")
            if e != 1:
                inv = tables.gf_inv(e)
                dist[cols:, j] = tables.gf_mul_np(dist[cols:, j], inv)
        # Make the first element of each later row one by scaling rows.
        for i in range(cols + 1, rows):
            e = int(dist[i, 0])
            if e == 0:
                raise ValueError("singular construction")
            if e != 1:
                inv = tables.gf_inv(e)
                dist[i, :] = tables.gf_mul_np(dist[i, :], inv)
    return dist


@functools.lru_cache(maxsize=None)
def reed_sol_van(k: int, m: int) -> np.ndarray:
    """(m, k) coding matrix: parity_i = sum_j M[i,j] * data_j."""
    dist = _systematize(extended_vandermonde(k + m, k), k)
    top = dist[:k]
    assert np.array_equal(top, np.eye(k, dtype=np.uint8)), \
        "systematic top block must be identity"
    return np.ascontiguousarray(dist[k:])


@functools.lru_cache(maxsize=None)
def cauchy_orig(k: int, m: int) -> np.ndarray:
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for cauchy at w=8")
    out = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i, j] = tables.gf_inv(i ^ (m + j))
    return out


@functools.lru_cache(maxsize=None)
def cauchy_good(k: int, m: int) -> np.ndarray:
    c = cauchy_orig(k, m).copy()
    for j in range(k):
        e = int(c[0, j])
        if e != 1:
            c[:, j] = tables.gf_mul_np(c[:, j], tables.gf_inv(e))
    return c


@functools.lru_cache(maxsize=None)
def reed_sol_r6_op(k: int, m: int) -> np.ndarray:
    """RAID-6 optimized RS: P = XOR of data, Q = sum 2^i * d_i
    (ref: jerasure reed_sol.c reed_sol_r6_coding_matrix; m must be 2)."""
    if m != 2:
        raise ValueError(f"reed_sol_r6_op requires m=2, got {m}")
    out = np.ones((2, k), dtype=np.uint8)
    acc = 1
    for i in range(1, k):
        acc = tables.gf_mul(acc, 2)
        out[1, i] = acc
    return out


TECHNIQUES = {
    "reed_sol_van": reed_sol_van,
    "reed_sol_r6_op": reed_sol_r6_op,
    "cauchy_orig": cauchy_orig,
    "cauchy_good": cauchy_good,
    # ISA-L's two techniques are the same constructions
    # (ref: src/erasure-code/isa/ErasureCodeIsa.cc).
    "cauchy": cauchy_good,
}

# Techniques defined as raw GF(2) bitmatrices over w packets per chunk
# (see ceph_tpu/ec/bitmatrix.py).
BITMATRIX_TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")


def coding_matrix(technique: str, k: int, m: int) -> np.ndarray:
    try:
        fn = TECHNIQUES[technique]
    except KeyError:
        raise ValueError(
            f"unknown technique {technique!r}; supported: "
            f"{sorted(TECHNIQUES)}") from None
    return fn(k, m)


def generator_matrix(technique: str, k: int, m: int) -> np.ndarray:
    """(k+m, k): identity stacked on the coding matrix (systematic code)."""
    return np.concatenate(
        [np.eye(k, dtype=np.uint8), coding_matrix(technique, k, m)], axis=0)


def decode_matrix(technique: str, k: int, m: int,
                  available: tuple[int, ...],
                  want: tuple[int, ...]) -> np.ndarray:
    """Rows reconstructing `want` chunk ids from `available` chunk ids.

    Returns (len(want), len(available)) GF matrix D with
    chunk[want] = D @ chunk[available].  available must contain >= k ids.
    This is the per-erasure-pattern inversion the reference caches
    (ref: src/erasure-code/isa/ErasureCodeIsaTableCache.cc).
    """
    g = generator_matrix(technique, k, m)
    avail = list(available)[:k]
    if len(avail) < k:
        raise ValueError(f"need {k} chunks to decode, have {len(available)}")
    sub = g[avail]                      # (k, k)
    inv = tables.gf_matinv_np(sub)      # data = inv @ chunks[avail]
    rows = g[list(want)]                # (w, k)
    d = tables.gf_matmul_np(rows, inv)  # (w, k) — over the k used chunks
    if len(available) > k:
        pad = np.zeros((len(want), len(available) - k), dtype=np.uint8)
        d = np.concatenate([d, pad], axis=1)
    return d
