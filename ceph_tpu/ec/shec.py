"""SHEC — Shingled Erasure Code.

ref: src/erasure-code/shec/ (ErasureCodeShec, shec_make_table). SHEC(k,m,c)
trades MDS-ness for cheap single-failure repair: each of the m parities
covers only a sliding window of ~k*c/m consecutive data chunks ("shingles"),
so repairing one data chunk reads a window (w+1 chunks) instead of k.
``c`` is the average number of parities covering each data chunk (the
durability estimator).

Construction here: window width w = ceil(k*c/m), parity i covers data
chunks [floor(i*k/m), floor(i*k/m)+w) clamped to k, with Cauchy
coefficients (any square Cauchy submatrix is invertible, which maximizes
the set of decodable erasure patterns a windowed code can have).

Provenance: the reference tree was empty during the survey (SURVEY.md
warning); the layout follows the published SHEC design, not upstream's
byte-exact tables.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile
from ceph_tpu.ec.jax_plugin import _MatrixKernel
from ceph_tpu.gf import tables
from ceph_tpu.utils.logging import get_logger

log = get_logger("ec")


def shec_matrix(k: int, m: int, c: int) -> np.ndarray:
    """(m, k) windowed Cauchy coding matrix; zeros outside each shingle."""
    if not (0 < c <= m <= k):
        raise ValueError(f"invalid shec geometry k={k} m={m} c={c}")
    w = -(-k * c // m)
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        start = (i * k) // m
        for j in range(start, min(start + w, k)):
            mat[i, j] = tables.gf_inv(i ^ (m + j))
    return mat


class ErasureCodeShec(ErasureCodeInterface):
    """plugin=shec k=K m=M c=C technique=multiple"""

    def __init__(self, profile: ErasureCodeProfile | str | None = None):
        super().__init__()
        self.c = 0
        self.matrix: np.ndarray | None = None
        self._kern: _MatrixKernel | None = None
        self._decode_cache: dict = {}
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 4)
        self.m = profile.get_int("m", 3)
        self.c = profile.get_int("c", 2)
        self.matrix = shec_matrix(self.k, self.m, self.c)
        self._kern = _MatrixKernel(self.matrix, "bitmatmul")
        self._decode_cache.clear()
        log.dout(5, "shec init", k=self.k, m=self.m, c=self.c)

    # -- structure queries ------------------------------------------------
    def parity_window(self, i: int) -> list[int]:
        """Data chunk ids covered by parity i."""
        return [j for j in range(self.k) if self.matrix[i, j]]

    def _generator(self) -> np.ndarray:
        return np.concatenate(
            [np.eye(self.k, dtype=np.uint8), self.matrix], axis=0)

    # -- encode -----------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        return np.asarray(self._kern.apply(jnp.asarray(data,
                                                       dtype=jnp.uint8)))

    # -- repair planning --------------------------------------------------
    def _repair_plan(self, want: set[int],
                     avail: set[int]) -> list[tuple[int, list[int]]] | None:
        """Iterative local repair: (target, reads) steps, or None.

        Each step reconstructs one missing chunk from one parity whose
        window is otherwise intact — the shingled fast path
        (ref: ErasureCodeShec minimum_to_decode search).
        """
        have = set(avail)
        plan: list[tuple[int, list[int]]] = []
        missing = set(want) - have
        for _ in range(len(missing) + 1):
            if not missing:
                return plan
            progress = False
            for t in sorted(missing):
                best: list[int] | None = None
                if t < self.k:
                    for i in range(self.m):
                        win = self.parity_window(i)
                        if t not in win or self.k + i not in have:
                            continue
                        reads = [j for j in win if j != t] + [self.k + i]
                        if all(r in have for r in reads) and (
                                best is None or len(reads) < len(best)):
                            best = reads
                else:
                    win = self.parity_window(t - self.k)
                    if all(j in have for j in win):
                        best = list(win)
                if best is not None:
                    plan.append((t, best))
                    have.add(t)
                    missing.discard(t)
                    progress = True
            if not progress:
                return None
        return plan

    def _solve_general(self, want: list[int],
                       avail: list[int]) -> np.ndarray | None:
        """Pick k GF-linearly-independent available generator rows via
        incremental Gauss elimination; returns (decode_matrix, rows) or
        None (SHEC is not MDS — some patterns are genuinely
        unrecoverable)."""
        g = self._generator()
        rows: list[int] = []
        reduced: list[np.ndarray] = []
        pivots: list[int] = []
        for r in sorted(avail):
            v = g[r].copy()
            for red, p in zip(reduced, pivots):
                if v[p]:
                    v = v ^ tables.gf_mul_np(int(v[p]), red)
            nz = np.flatnonzero(v)
            if not nz.size:
                continue
            piv = int(nz[0])
            v = tables.gf_mul_np(tables.gf_inv(int(v[piv])), v)
            rows.append(r)
            reduced.append(v)
            pivots.append(piv)
            if len(rows) == self.k:
                break
        if len(rows) < self.k:
            return None
        inv = tables.gf_matinv_np(g[rows])
        d = tables.gf_matmul_np(g[list(want)], inv)
        return d, rows

    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> set[int]:
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        plan = self._repair_plan(want, avail)
        if plan is not None:
            reads = set(want & avail)
            produced: set[int] = set()
            for t, rs in plan:
                reads |= {r for r in rs if r not in produced}
                produced.add(t)
            return reads & avail
        solved = self._solve_general(sorted(want - avail), sorted(avail))
        if solved is None:
            raise ValueError(
                f"shec cannot decode {sorted(want - avail)} from "
                f"{sorted(avail)}")
        _, rows = solved
        return set(rows) | (want & avail)

    # -- decode -----------------------------------------------------------
    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        have = {i: np.asarray(c, dtype=np.uint8)
                for i, c in chunks.items()}
        missing = [i for i in want if i not in have]
        plan = self._repair_plan(set(want), set(have))
        if plan is not None:
            g = self._generator()
            for t, reads in plan:
                if t >= self.k:
                    row = self.matrix[t - self.k]
                    acc = np.zeros_like(have[reads[0]])
                    for j in reads:
                        acc ^= tables.gf_mul_np(row[j], have[j])
                    have[t] = acc
                else:
                    # t = (parity - sum others) / coef_t within the window
                    pi = reads[-1] - self.k
                    row = self.matrix[pi]
                    acc = have[self.k + pi].copy()
                    for j in reads[:-1]:
                        acc ^= tables.gf_mul_np(row[j], have[j])
                    have[t] = tables.gf_mul_np(
                        tables.gf_inv(int(row[t])), acc)
            return {i: have[i] for i in want}
        solved = self._solve_general(missing, sorted(have))
        if solved is None:
            raise ValueError(
                f"shec cannot decode {missing} from {sorted(have)}")
        d, rows = solved
        stacked = np.stack([have[r] for r in rows])
        out = tables.gf_matmul_np(d, stacked)
        res = {i: have[i] for i in want if i in have}
        for idx, i in enumerate(missing):
            res[i] = out[idx]
        return res
