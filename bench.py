"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline (BASELINE.md north star): EC encode throughput at k=8, m=3 on
4 MiB objects — the ``ceph_erasure_code_benchmark plugin=isa k=8 m=3``
configuration. ``vs_baseline`` compares against 7.5 GiB/s, the midpoint of
the ISA-L single-core estimate recorded in BASELINE.md (the reference
publishes no numbers in-repo).

Runs on whatever platform is live (the driver provides one real TPU chip).
"""

import json
import os
import sys
import time

BASELINE_GIBS = 7.5  # ISA-L RS k=8,m=3 single-core (BASELINE.md external row)


def main() -> None:
    from ceph_tpu.bench.ec_benchmark import ErasureCodeBench, parse_args

    backend = os.environ.get("CEPH_TPU_BENCH_BACKEND", "bitmatmul")
    iters = int(os.environ.get("CEPH_TPU_BENCH_ITERS", "1024"))
    args = parse_args([
        "--plugin", "jax", "--workload", "encode",
        "--size", str(4 << 20), "--iterations", str(iters),
        "--parameter", "k=8", "--parameter", "m=3",
        "--parameter", f"backend={backend}",
        "--parameter", "technique=reed_sol_van",
    ])
    bench = ErasureCodeBench(args)
    res = bench.run()
    print(json.dumps({
        "metric": "ec_encode_k8m3_4MiB",
        "value": round(res["GiB/s"], 3),
        "unit": "GiB/s",
        "vs_baseline": round(res["GiB/s"] / BASELINE_GIBS, 3),
        "detail": {
            "seconds": round(res["seconds"], 4),
            "iterations": res["iterations"],
            "batch": res["batch"],
            "backend": res["backend"],
            "platform": res["platform"],
        },
    }))


if __name__ == "__main__":
    main()
