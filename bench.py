"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline (BASELINE.md north star): EC encode throughput at k=8, m=3 on
4 MiB objects — the ``ceph_erasure_code_benchmark plugin=isa k=8 m=3``
configuration. ``vs_baseline`` compares against 7.5 GiB/s, the midpoint of
the ISA-L single-core estimate recorded in BASELINE.md (the reference
publishes no numbers in-repo).

Methodology note (round 2): round 1's number (9,317 GiB/s) was measured
with a dispatch-timed loop and is RETRACTED — on this platform
``block_until_ready`` returns before execution. All rates here come from
the chained readback-anchored slope method (ceph_tpu/utils/timing.py) and
pass the physical roofline guard (ceph_tpu/utils/roofline.py); the
methodology fields are included in the output so the number can be audited.

Secondary metrics in ``detail``: decode throughput, MFU, and the CRUSH
north-star ``crush_mappings_per_s`` (batched pg->osd mapping rate).
"""

import json
import os
import re
import sys
import time
import traceback

BASELINE_GIBS = 7.5  # ISA-L RS k=8,m=3 single-core (BASELINE.md external row)

_ANSI = re.compile(r"\x1b\[[0-9;]*m")


def _short_err(limit: int = 400) -> str:
    """Compact one-line rendering of the current exception.

    Round 4's lesson: a raw ``format_exc`` of a TPU compile error embeds
    kilobytes of runtime log (with ANSI escapes) into the JSON line and
    the driver fails to parse it — the whole round's number is lost.
    Strip escapes, keep the last few non-empty lines, hard-cap length."""
    s = _ANSI.sub("", traceback.format_exc(limit=2))
    lines = [ln.strip() for ln in s.splitlines() if ln.strip()]
    return " | ".join(lines[-4:])[:limit]


def ec_metrics() -> tuple[dict, dict, dict]:
    from ceph_tpu.bench.ec_benchmark import ErasureCodeBench, parse_args

    # "auto" resolves to the fused pallas kernel on TPU — tested
    # byte-exact vs the XLA path (tests/test_gf.py TestPallasKernel) and
    # measured ~1.7x bitmatmul on v5e (~103 vs ~60 GiB/s) after the
    # round-4 rewrite (mod-2 absorb + block-diag contraction) — and to
    # bitmatmul elsewhere (pallas would only interpret on CPU).
    backend = os.environ.get("CEPH_TPU_BENCH_BACKEND", "auto")
    common = [
        "--plugin", "jax", "--size", str(4 << 20),
        "--parameter", "k=8", "--parameter", "m=3",
        "--parameter", f"backend={backend}",
        "--parameter", "technique=reed_sol_van",
    ]
    enc = ErasureCodeBench(parse_args(
        common + ["--iterations", "1024", "--workload", "encode",
                  "--slope-steps", "16", "96"])).run()
    dec = ErasureCodeBench(parse_args(
        common + ["--iterations", "1024", "--workload", "decode",
                  "--erasures", "2", "--slope-steps", "16", "96"])).run()
    # Streamed row (SURVEY §7: report resident AND streamed): H2D inside
    # the loop. Small steps — on this sandbox H2D rides the axon network
    # tunnel (~6 MB/s measured), so the row documents the honest
    # host-transfer-bound rate of THIS platform, not a PCIe number.
    stream = ErasureCodeBench(parse_args(
        common + ["--iterations", "8", "--batch", "8",
                  "--workload", "encode", "--stream"])).run()
    return enc, dec, stream


def ec_streaming_metric(resident_gibs: float | None) -> dict:
    """Round-13 EC data path at production traffic: the cross-op
    encode aggregator (concurrent ops coalescing into padded batched
    launches vs the per-op `osd_ec_agg=off` baseline) and the
    double-buffered H2D/D2H streaming pipeline, against the resident
    kernel rate. The claim the section pins: aggregated multi-op
    encode throughput within 2x of the resident number on TPU
    (`ec_agg_within_2x` in the compact tail; CPU boxes run a smoke
    size with the same schema)."""
    from ceph_tpu.bench.ec_streaming import ec_streaming_section

    return ec_streaming_section(resident_gibs=resident_gibs)


def ec_daemon_path_metric() -> dict:
    """Round-19 read-side data path: concurrent degraded-read decodes
    through the ``osd/ec_read_aggregator`` (coalesced padded batched
    decode launches vs the per-op ``osd_ec_read_agg=off`` baseline),
    against the resident decode kernel rate. The claim the section
    pins: the aggregated daemon-path rate lands within 2x of the
    resident number on TPU (``daemon_within_2x_resident`` in the
    compact tail; CPU boxes run a smoke size with the same schema and
    an explicit asyncio-bound caveat)."""
    from ceph_tpu.bench.ec_daemon_path import ec_daemon_path_section

    return ec_daemon_path_section()


def crush_metric() -> dict:
    """North-star #2: batched CRUSH mappings/s on a 10k-OSD straw2 map.

    Headline = uniform map (the fused Pallas kernel path on TPU);
    ``variants`` adds the production-shaped mixed-weight and
    choose_args rates so the slow paths are measured every round
    (VERDICT r3 Weak #3)."""
    from ceph_tpu.bench.crush_sweep import sweep_rate, sweep_rate_variants

    n_pgs = int(os.environ.get("CEPH_TPU_BENCH_CRUSH_PGS", str(1 << 21)))
    res = sweep_rate(n_osds=10240, n_pgs=n_pgs, num_rep=3)
    # LOUD (round 10): a row whose built kernel plan silently degraded
    # to xla/scalar mid-run is a recorded regression, not a mystery
    # slowdown — the PR 4 choose_args cliff hid here. The headline
    # row's verdict must survive even when the variants pass crashes.
    regs = []
    if "path_expected_vs_actual" in res:
        regs.append(f"uniform: {res['path_expected_vs_actual']}")
    try:
        res["variants"] = sweep_rate_variants(
            n_osds=10240, n_pgs=n_pgs, num_rep=3,
            variants=("mixed_weight", "choose_args",
                      "choose_args_quantized"))
        from ceph_tpu.bench.crush_sweep import path_regressions
        regs += path_regressions(res["variants"])
    except Exception:
        res["variants_error"] = _short_err()
    if regs:
        res["path_regressions"] = regs
    return res


def crush_multichip_metric(single_rate: float | None) -> dict:
    """Round-10 pod-scale row: a MEASURED full sweep on a mesh over
    every available device (the v5e-8's 8 chips under the driver; a
    single chip degenerates to a 1-device mesh) — the number the
    paper's ≈5 s pod figure only ever estimated via linear scaling.
    ``seconds_100M`` is the measured wall itself at the default
    100M-PG target (``extrapolated: false``); per-device scaling
    efficiency is reported against the single-chip row."""
    import jax

    from ceph_tpu.bench.crush_sweep import canonical_map, sweep_rate
    from ceph_tpu.bench.multichip import measured_sweep
    from ceph_tpu.crush.mapper import Mapper
    from ceph_tpu.parallel import make_mesh

    devices = jax.devices()
    # the full 100M target is a TPU-rate number; a CPU dev box running
    # bench.py would spend hours on it through the rule VM — default
    # to a smoke size there (env override always wins)
    default_pgs = 100_000_000 \
        if devices[0].platform == "tpu" else 1 << 20
    n_pgs = int(os.environ.get("CEPH_TPU_BENCH_MULTICHIP_PGS",
                               str(default_pgs)))
    mesh = make_mesh(devices)
    mapper = Mapper(canonical_map(10240))
    res = measured_sweep(mesh, mapper, n_pgs, 3)
    if single_rate is None:
        single_rate = sweep_rate(n_osds=10240, n_pgs=1 << 21,
                                 num_rep=3)["mappings_per_s"]
    res["single_device_mappings_per_s"] = single_rate
    res["scaling_efficiency"] = round(
        res["mappings_per_s"] / (single_rate * len(devices)), 3)
    return res


def balancer_metric() -> dict:
    """Balancer convergence at scale (VERDICT r3 ask #10): wall time of
    calc_pg_upmaps on a canonical-scale map, plus the Mapper lifecycle
    counter DELTAS for the run — pack/compile traffic at 10k OSDs is a
    recorded number now, not a guess."""
    from ceph_tpu.bench import osdmaptool
    from ceph_tpu.crush.mapper import PERF

    n_osds = int(os.environ.get("CEPH_TPU_BENCH_BAL_OSDS", "10240"))
    pgs = int(os.environ.get("CEPH_TPU_BENCH_BAL_PGS", "16384"))
    iters = int(os.environ.get("CEPH_TPU_BENCH_BAL_ITERS", "40"))
    t0 = time.perf_counter()
    m = osdmaptool.create_simple(n_osds, pgs, 3, erasure=False)
    build_s = time.perf_counter() - t0
    before = PERF.dump()
    t0 = time.perf_counter()
    changes = m.calc_pg_upmaps(max_deviation=5, max_iterations=iters)
    bal_s = time.perf_counter() - t0
    after = PERF.dump()
    counters = {k: round(after[k] - before[k], 4)
                for k in after if isinstance(after[k], (int, float))}
    return {"n_osds": n_osds, "pg_num": pgs, "max_iterations": iters,
            "upmap_changes": changes,
            "build_seconds": round(build_s, 3),
            "balance_seconds": round(bal_s, 3),
            "seconds_per_iteration": round(bal_s / max(iters, 1), 4),
            "mapper_counter_deltas": counters}


def mapping_engine_metric() -> dict:
    """Round-6 serving layers: the delta-remap path of OSDMapMapping
    (one-OSD incremental: remapped PGs + wall time vs a from-scratch
    resweep) and the epoch-keyed scalar cache hit rate — the numbers
    behind 'steady-state ops never re-enter the mapper'."""
    from ceph_tpu.bench import osdmaptool
    from ceph_tpu.osd.osdmap import Incremental
    from ceph_tpu.osd.osdmap_mapping import OSDMapMapping

    n_osds = int(os.environ.get("CEPH_TPU_BENCH_MAP_OSDS", "1024"))
    pgs = int(os.environ.get("CEPH_TPU_BENCH_MAP_PGS", "8192"))
    m = osdmaptool.create_simple(n_osds, pgs, 3, erasure=False)
    t0 = time.perf_counter()
    mm = OSDMapMapping(m)
    initial_s = time.perf_counter() - t0
    m.apply_incremental(Incremental(epoch=m.epoch + 1, new_down=[7]))
    t0 = time.perf_counter()
    mm.update(m)
    delta_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    OSDMapMapping(m)
    scratch_s = time.perf_counter() - t0
    # scalar memo (no table attached yet): one miss fills the
    # per-epoch memo, repeated op-targeting lookups hit it
    m.mapping_cache_hits = m.mapping_cache_misses = 0
    for _ in range(256):
        m.pg_to_acting_primary(1, 5)
    memo_hits, memo_misses = (m.mapping_cache_hits,
                              m.mapping_cache_misses)
    # attached table: serves every lookup at its epoch outright
    m.attach_mapping(mm)
    m.mapping_cache_hits = m.mapping_cache_misses = 0
    for _ in range(256):
        m.pg_to_acting_primary(1, 5)
    return {"n_osds": n_osds, "pg_num": pgs,
            "initial_sweep_seconds": round(initial_s, 4),
            "delta_update_seconds": round(delta_s, 4),
            "delta_remap_pgs": mm.last_remap_pgs,
            "full_resweep_seconds": round(scratch_s, 4),
            "delta_speedup": round(scratch_s / max(delta_s, 1e-9), 1),
            "memo_hits": memo_hits,
            "memo_misses": memo_misses,
            "cache_hits": m.mapping_cache_hits,
            "cache_misses": m.mapping_cache_misses}


def mds_metric() -> dict:
    """Round-7 metadata plane: aggregate + per-rank metadata ops/s at
    N = 1/2/4 active MDS ranks. FIXED client parallelism (4 writers,
    each its own client + subtree) distributed round-robin across the
    ranks, so the rows isolate rank scaling rather than client
    scaling — per rank, mutations serialize on that rank's journal
    object (per-object PG pipeline), which is exactly the contention
    multi-active relieves. The number that must move: aggregate ops/s
    increasing 1 -> 2 actives (rank-scaling regressions show here)."""
    import asyncio

    async def one(n_active: int, writers: int = 4,
                  ops_per_writer: int = 24) -> dict:
        from ceph_tpu.cephfs.client import CephFSClient
        from ceph_tpu.cluster.vstart import Cluster
        c = await Cluster(n_mons=1, n_osds=3,
                          config={"mds_bal_interval": 0.0}).start()
        try:
            await c.start_fs(n_mds=n_active, max_mds=n_active,
                             timeout=120)
            monmap = c.client.monc.monmap
            cl0 = await CephFSClient.create(monmap, None, "cephfs",
                                            keyring=c.keyring)
            for w in range(writers):
                await cl0.mkdir(f"/d{w}")
                if w % n_active:
                    await c.subtree_pin(f"/d{w}", w % n_active)
            clients = [cl0] + [
                await CephFSClient.create(monmap, None, "cephfs",
                                          keyring=c.keyring)
                for _ in range(1, writers)]

            async def load(w: int, cl) -> float:
                t0 = time.perf_counter()
                for i in range(ops_per_writer):
                    await cl.write_file(f"/d{w}/bench-{i}",
                                        b"x" * 64)
                return ops_per_writer / (time.perf_counter() - t0)
            t0 = time.perf_counter()
            rates = await asyncio.gather(
                *[load(w, cl) for w, cl in enumerate(clients)])
            wall = time.perf_counter() - t0
            per_rank: dict[str, float] = {}
            for w, rate in enumerate(rates):
                r = str(w % n_active)
                per_rank[r] = round(per_rank.get(r, 0.0) + rate, 1)
            for cl in clients:
                await cl.unmount()
            return {
                "ops": writers * ops_per_writer,
                "writers": writers,
                "aggregate_ops_per_s": round(
                    writers * ops_per_writer / wall, 1),
                "per_rank_ops_per_s": per_rank,
            }
        finally:
            await c.stop()

    return {f"max_mds_{n}": asyncio.run(one(n)) for n in (1, 2, 4)}


def tracing_metric() -> dict:
    """Round-9 observability layer: ops/s on the replicated cluster
    write path at trace_sampling_rate 0.0 vs 1.0, plus a tracing-off
    baseline (trace_slow_keep_s=0 disables even the tail-retention
    timing). The number that must hold: the DISABLED path
    (sampling 0, tail tracking on — the production default) stays
    within noise (<5%) of the off baseline; full sampling's cost is
    reported so the layer's price is pinned in the BENCH trajectory."""
    import asyncio

    async def one(rate: float, slow_keep: float,
                  n_ops: int = 160) -> float:
        from ceph_tpu.cluster.vstart import Cluster
        c = await Cluster(n_mons=1, n_osds=3, config={
            "trace_sampling_rate": rate,
            "trace_slow_keep_s": slow_keep}).start()
        try:
            await c.client.pool_create("bench", pg_num=8)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("bench")
            for i in range(24):                      # warm the path
                await io.write_full(f"warm-{i}", b"x" * 1024)
            t0 = time.perf_counter()
            for i in range(n_ops):
                await io.write_full(f"obj-{i % 16}", b"x" * 1024)
            return n_ops / (time.perf_counter() - t0)
        finally:
            await c.stop()

    off = asyncio.run(one(0.0, 0.0))          # layer fully off
    disabled = asyncio.run(one(0.0, 30.0))    # default: tail-only
    full = asyncio.run(one(1.0, 30.0))        # every op traced
    disabled_overhead = (off - disabled) / off * 100.0
    full_overhead = (off - full) / off * 100.0
    return {
        "write_ops_per_s_tracing_off": round(off, 1),
        "write_ops_per_s_sampling_0": round(disabled, 1),
        "write_ops_per_s_sampling_1": round(full, 1),
        "disabled_overhead_pct": round(disabled_overhead, 2),
        "full_sampling_overhead_pct": round(full_overhead, 2),
        # the assertion the satellite pins: disabled-path cost is
        # noise (single-run cluster benches jitter a few percent, so
        # the flag — not a hard error — records the verdict)
        "disabled_within_noise": bool(disabled_overhead < 5.0),
    }


def telemetry_metric() -> dict:
    """Round-12 telemetry plane: cluster write-path ops/s with the
    daemon->mgr report loop OFF (mgr_stats_period=0), at the default
    period, and at 10x the period. The number that must hold: the
    default report loop stays within noise (<5%) of the off baseline
    (``telemetry_within_noise`` in the compact tail line) — same
    verdict shape as the round-9 tracing section. Unlike that
    section, all three legs run inside ONE cluster by flipping the
    LIVE ``mgr_stats_period`` knob (the shared-cfg dict pattern):
    separate cluster spins in one process jitter >10% run-to-run,
    which would swamp the report loop's actual cost — in-cluster
    A/B/A alternation with a median collapses that to per-burst
    noise."""
    import asyncio
    import statistics

    async def measure() -> dict[float, float]:
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.mgr.modules import PrometheusModule
        c = await Cluster(n_mons=1, n_osds=3,
                          config={"mgr_stats_period": 0.0},
                          mgr_modules=[PrometheusModule]).start()
        try:
            await c.client.pool_create("bench", pg_num=8)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("bench")
            for i in range(24):                      # warm the path
                await io.write_full(f"warm-{i}", b"x" * 1024)
            samples: dict[float, list[float]] = {
                0.0: [], 0.25: [], 2.5: []}
            order = list(samples)
            for rep in range(5):
                # rotate the leg order per rep: within-cluster drift
                # (PG logs filling toward their trim cap, allocator
                # state) is monotone in time, and a constant order
                # would charge it to whichever leg always runs last
                rot = rep % len(order)
                for period in order[rot:] + order[:rot]:
                    c.cfg["mgr_stats_period"] = period
                    await asyncio.sleep(0.6)  # loops read it LIVE
                    t0 = time.perf_counter()
                    for i in range(160):
                        await io.write_full(f"obj-{i % 16}",
                                            b"x" * 1024)
                    samples[period].append(
                        160 / (time.perf_counter() - t0))
            return samples
        finally:
            await c.stop()

    samples = asyncio.run(measure())
    legs = {p: statistics.median(v) for p, v in samples.items()}
    off = legs[0.0]                      # report loop disabled
    default = legs[0.25]                 # the vstart default period
    slow10 = legs[2.5]                   # 10x period
    overhead = (off - default) / off * 100.0
    # the off leg's own within-run spread IS the measurement's noise
    # floor (shared boxes schedule-jitter way past 5%): the verdict
    # asks whether the default report loop's cost is distinguishable
    # from that floor, and both raw numbers stay in the record
    spread = (max(samples[0.0]) - min(samples[0.0])) / off * 100.0
    return {
        "write_ops_per_s_reporting_off": round(off, 1),
        "write_ops_per_s_default_period": round(default, 1),
        "write_ops_per_s_10x_period": round(slow10, 1),
        "report_overhead_pct": round(overhead, 2),
        "noise_floor_pct": round(spread, 2),
        # the flag — not a hard error — records the verdict
        "telemetry_within_noise": bool(
            overhead < max(5.0, spread)),
    }


def qos_metric() -> dict:
    """Round-11 op-QoS layer: a 2-tenant hot/cold mix — ops/s + p99
    for the COLD tenant at its solo baseline, under FIFO admission,
    and under the dmClock scheduler. The claim the section pins: the
    scheduler holds the cold tenant's p99 within 2x of its solo run
    while FIFO (hot tenant at ~10x offered load) does not
    (``scheduler_protects_cold``)."""
    import asyncio

    async def run() -> dict:
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.msg import Keyring as _Keyring
        from ceph_tpu.rados import Rados as _Rados
        from ceph_tpu.sim.thrasher import Thrasher
        c = await Cluster(n_mons=1, n_osds=3, config={
            # a small dispatch cap makes admission ordering the
            # bottleneck (the thing being measured), not store speed
            "osd_client_message_cap": 4,
            "osd_op_queue": "mclock"}).start()
        try:
            await c.client.pool_create("qos", pg_num=8)
            await c.wait_for_clean(timeout=120)
            ret, rs, out = await c.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.cold"})
            assert ret == 0, rs
            key = bytes.fromhex(json.loads(out)["key"])
            cold = _Rados(c.monmap, name="client.cold",
                          keyring=_Keyring({"client.cold": key}),
                          config=c.cfg)
            await cold.connect()
            io_cold = await cold.open_ioctx("qos")
            io_hot = await c.client.open_ioctx("qos")
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd client-profile", "op": "set",
                 "entity": "client.cold", "reservation": 20.0,
                 "weight": 4.0, "limit": 0.0})
            assert ret == 0, rs
            # settle + warm: the profile commit bumps the map epoch
            # and first ops pay connection setup — keep both out of
            # the solo baseline
            await c.wait_for_clean(timeout=60)
            for i in range(6):
                await io_cold.write_full(f"warm-c-{i}", b"w" * 256)
                await io_hot.write_full(f"warm-h-{i}", b"w" * 256)
            th = Thrasher(c, seed=7)
            solo = await th.qos_storm(io_cold, io_hot, writes=24,
                                      hot_parallel=0)
            c.cfg["osd_op_queue"] = "fifo"
            fifo = await th.qos_storm(io_cold, io_hot, writes=24,
                                      hot_parallel=4, hot_burst=16)
            c.cfg["osd_op_queue"] = "mclock"
            mclock = await th.qos_storm(io_cold, io_hot, writes=24,
                                        hot_parallel=4, hot_burst=16)
            await cold.shutdown()
            # the verdict compares p95 (structural queueing delay) —
            # at this sample count p99 is the max, owned by one
            # GC/event-loop blip; p99s stay in the record
            floor = max(2.0 * solo["cold_p99_s"], 0.05)
            return {
                "cold_solo": solo, "cold_under_fifo": fifo,
                "cold_under_mclock": mclock,
                "fifo_p99_ratio": round(
                    fifo["cold_p99_s"] /
                    max(solo["cold_p99_s"], 1e-9), 2),
                "mclock_p99_ratio": round(
                    mclock["cold_p99_s"] /
                    max(solo["cold_p99_s"], 1e-9), 2),
                "scheduler_protects_cold": bool(
                    mclock["cold_p95_s"] <= floor <
                    fifo["cold_p95_s"]),
            }
        finally:
            await c.stop()

    return asyncio.run(run())


def tuning_metric() -> dict:
    """Round-17 self-driving tuner: the hot-pool-burst storm with the
    mgr TunerModule ``off`` (static config) vs ``drive`` (closing the
    loop), both legs inside ONE cluster with the leg order rotated
    per rep and medians across reps (the round-12 in-cluster A/B
    discipline — separate cluster spins jitter >10%). The claim the
    section pins: in drive mode the tuner's hot-pool protector
    commits a tightened client-profile on the aggressor and the cold
    tenant's p95 stays at-or-under the static run's, without
    collapsing aggregate throughput (``tuner_protects_cold``)."""
    import asyncio
    import statistics

    async def run() -> dict:
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.mgr.tuner import TunerModule
        from ceph_tpu.msg import Keyring as _Keyring
        from ceph_tpu.rados import Rados as _Rados
        from ceph_tpu.sim.thrasher import Thrasher
        c = await Cluster(n_mons=1, n_osds=3,
                          mgr_modules=[TunerModule], config={
            "osd_client_message_cap": 4,
            "osd_op_queue": "mclock",
            "mgr_tuner_mode": "off",
            # smoke-speed control loop: fast ticks, short hysteresis,
            # trip threshold sized to the storm's offered load, pg
            # stats refreshed faster than the tick so consecutive
            # breach windows see fresh rates
            "osd_stats_interval": 0.1,
            "mgr_tuner_interval": 0.2,
            "mgr_tuner_act_ticks": 2,
            "mgr_tuner_revert_ticks": 4,
            "mgr_tuner_hot_pool_min_ops": 5.0,
            # keep the recovery governor quiet (no backfill here):
            # the section isolates the hot-pool protector
            "mgr_tuner_qos_floor_ms": 5000.0}).start()
        try:
            await c.client.pool_create("cold", pg_num=8)
            await c.client.pool_create("hot", pg_num=8)
            await c.wait_for_clean(timeout=120)

            async def tenant(entity: str) -> _Rados:
                ret, rs, out = await c.client.mon_command(
                    {"prefix": "auth get-or-create",
                     "entity": entity})
                assert ret == 0, rs
                key = bytes.fromhex(json.loads(out)["key"])
                r = _Rados(c.monmap, name=entity,
                           keyring=_Keyring({entity: key}),
                           config=c.cfg)
                await r.connect()
                return r
            cold = await tenant("client.cold")
            hot = await tenant("client.hot")
            io_cold = await cold.open_ioctx("cold")
            io_hot = await hot.open_ioctx("hot")
            await c.wait_for_clean(timeout=60)
            for i in range(6):
                await io_cold.write_full(f"warm-c-{i}", b"w" * 256)
                await io_hot.write_full(f"warm-h-{i}", b"w" * 256)
            th = Thrasher(c, seed=17)
            samples: dict[str, list[dict]] = {"off": [], "drive": []}
            committed = reverted = 0
            order = ["off", "drive"]
            for rep in range(2):
                rot = rep % len(order)
                for leg in order[rot:] + order[:rot]:
                    ret, _, out = await c.client.mon_command(
                        {"prefix": "tune status"})
                    before = json.loads(out) if ret == 0 else {}
                    c.cfg["mgr_tuner_mode"] = leg   # read LIVE per tick
                    r = await th.tuner_storm(
                        io_cold, io_hot, writes=24, hot_parallel=4,
                        hot_burst=16, ramp_s=1.0)
                    samples[leg].append(r)
                    if leg == "drive" and r.get("tuner"):
                        committed += max(0, r["tuner"].get(
                            "committed", 0) - before.get("committed", 0))
                        reverted += max(0, r["tuner"].get(
                            "reverted", 0) - before.get("reverted", 0))
                    # restore the static config between legs: a
                    # tuner-committed profile must not leak into an
                    # off leg (the operator rm releases its lease)
                    c.cfg["mgr_tuner_mode"] = "off"
                    for ent in ("client.hot", "client.cold"):
                        await c.client.mon_command(
                            {"prefix": "osd client-profile",
                             "op": "rm", "entity": ent})
                    await c.wait_for_clean(timeout=60)
            await cold.shutdown()
            await hot.shutdown()

            def med(leg: str, key: str) -> float:
                return statistics.median(
                    x[key] for x in samples[leg])
            off_p95, drv_p95 = med("off", "cold_p95_s"), \
                med("drive", "cold_p95_s")
            off_agg, drv_agg = med("off", "agg_ops_per_s"), \
                med("drive", "agg_ops_per_s")
            return {
                "off": {"cold_p95_s": round(off_p95, 4),
                        "cold_p99_s": round(
                            med("off", "cold_p99_s"), 4),
                        "agg_ops_per_s": off_agg},
                "drive": {"cold_p95_s": round(drv_p95, 4),
                          "cold_p99_s": round(
                              med("drive", "cold_p99_s"), 4),
                          "agg_ops_per_s": drv_agg},
                "cold_p99_ratio_drive_vs_off": round(
                    med("drive", "cold_p99_s") /
                    max(med("off", "cold_p99_s"), 1e-9), 2),
                "agg_ops_delta_pct": round(
                    (drv_agg - off_agg) / max(off_agg, 1e-9) * 100,
                    1),
                "actions_committed": committed,
                "actions_reverted": reverted,
                # p95 for the verdict (smoke-count p99 is the max);
                # "protects" = no worse for the cold tenant, actions
                # actually landed, throughput not collapsed
                "tuner_protects_cold": bool(
                    drv_p95 <= off_p95 * 1.05 and committed >= 1 and
                    drv_agg >= 0.5 * off_agg),
            }
        finally:
            await c.stop()

    return asyncio.run(run())


def device_resilience_metric() -> dict:
    """Round-16 device-fault resilience plane, two legs:

    (a) **no-fault overhead** — the price of the ``jit_call`` fault
    chokepoint when nothing fires: sweep rate with no injector vs an
    ARMED injector whose device rules never match (the armed path
    pays ``str(key)`` + rule iteration on every device call — exactly
    what production pays while a fault set is installed). The verdict
    the satellite pins: ``resilience_within_noise`` — the armed rate
    stays within noise (<5%) of the bare rate.

    (b) **degrade / re-promote cycle** — one injected kernel-path
    failure on an interpret-mode kernel mapper at
    ``crush_kernel_reprobe_base=0``: wall from the fault to the
    XLA-served answer (the client never errors), and wall back to the
    earned (bit-exact probed) re-promotion."""
    import jax

    from ceph_tpu.bench.crush_sweep import canonical_map, sweep_rate
    from ceph_tpu.crush.mapper import Mapper
    from ceph_tpu.sim import faults as F
    from ceph_tpu.utils import devmon as devmon_mod

    default_pgs = 1 << 20 \
        if jax.devices()[0].platform == "tpu" else 1 << 16
    n_pgs = int(os.environ.get("CEPH_TPU_BENCH_RESIL_PGS",
                               str(default_pgs)))
    mapper = Mapper(canonical_map(1024))
    base = sweep_rate(n_osds=1024, n_pgs=n_pgs, num_rep=3,
                      mapper=mapper)
    inj = F.FaultInjector(seed=16)
    # a device rule that can never match keeps has_device_rules()
    # true, so every jit_call walks the armed slow path
    inj.install("bench_armed",
                [F.jit_fail("bench_no_such_fn", key="never")])
    devmon_mod.set_fault_injector(inj)
    try:
        armed = sweep_rate(n_osds=1024, n_pgs=n_pgs, num_rep=3,
                           mapper=mapper)
    finally:
        devmon_mod.set_fault_injector(None)
    overhead = (base["mappings_per_s"] - armed["mappings_per_s"]) \
        / base["mappings_per_s"] * 100.0
    return {
        "no_fault": {
            "n_pgs": n_pgs,
            "mappings_per_s_bare": base["mappings_per_s"],
            "mappings_per_s_armed": armed["mappings_per_s"],
            "overhead_pct": round(overhead, 2),
            # single-run sweeps jitter a few percent — the flag (not
            # a hard error) records the verdict, loudly
            "resilience_within_noise": bool(overhead < 5.0),
        },
        "fault_cycle": _device_fault_cycle(F, devmon_mod),
    }


def snapshot_metric() -> dict:
    """Round-20 snapshot plane: snap_create and rbd clone wall vs
    image bytes at 1x/8x/64x (each image is ONE data object, so the
    64x row is a 64x-bigger object), plus the first-overwrite-after-
    snap COW cost vs a plain overwrite. Snapshots and clones are
    O(metadata) — a snap cut is a header mutation plus a selfmanaged
    snap id, a clone is a child header pointing at the parent snap,
    and the OSD-side COW is a BlueStore shared-blob ``t.clone`` that
    bumps refcounts instead of copying extents — so NONE of the three
    walls may scale with data size. The claim the section pins:
    ``clone_is_ometa`` — the 64x/1x wall ratio for snap_create, clone
    AND first-overwrite COW overhead all stay far under the 64x data
    ratio (threshold: < 8x)."""
    import asyncio
    import math
    import statistics

    base = int(os.environ.get("CEPH_TPU_BENCH_SNAP_BASE",
                              str(16 << 10)))

    async def one(mult: int) -> dict:
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.rbd import RBD
        size = base * mult
        order = max(12, math.ceil(math.log2(size)))
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("snapbench", pg_num=8)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("snapbench")
            rbd = RBD(io)
            # plain-overwrite control: same size, never snapped
            await rbd.create("plain", size, order=order)
            plain = await rbd.open("plain")
            await plain.write(0, b"p" * size)
            plain_walls = []
            for i in range(3):
                t0 = time.perf_counter()
                await plain.write(0, bytes([i]) * size)
                plain_walls.append(time.perf_counter() - t0)
            await rbd.create("img", size, order=order)
            img = await rbd.open("img")
            await img.write(0, b"d" * size)
            snap_walls, cow_walls, clone_walls = [], [], []
            for i in range(3):
                t0 = time.perf_counter()
                await img.snap_create(f"s{i}")
                snap_walls.append(time.perf_counter() - t0)
                # first overwrite under the new snap: the OSD clones
                # the head object (shared-blob COW) before applying
                t0 = time.perf_counter()
                await img.write(0, bytes([65 + i]) * size)
                cow_walls.append(time.perf_counter() - t0)
            await img.snap_protect("s0")
            for i in range(3):
                t0 = time.perf_counter()
                await rbd.clone("img", "s0", f"child-{i}")
                clone_walls.append(time.perf_counter() - t0)
            med = statistics.median
            return {"image_bytes": size,
                    "snap_create_ms": round(med(snap_walls) * 1e3, 3),
                    "clone_ms": round(med(clone_walls) * 1e3, 3),
                    "cow_overwrite_ms": round(med(cow_walls) * 1e3, 3),
                    "plain_overwrite_ms": round(
                        med(plain_walls) * 1e3, 3)}
        finally:
            await c.stop()

    async def run() -> dict:
        rows = {f"{m}x": await one(m) for m in (1, 8, 64)}
        r1, r64 = rows["1x"], rows["64x"]

        def ratio(key: str) -> float:
            return round(r64[key] / max(r1[key], 1e-6), 2)
        # the COW verdict compares the COW *overhead* (cow minus
        # plain at the same size): the raw write wall legitimately
        # scales with the payload, the clone it pays must not
        cow_over_1 = max(
            r1["cow_overwrite_ms"] - r1["plain_overwrite_ms"], 1e-3)
        cow_over_64 = max(
            r64["cow_overwrite_ms"] - r64["plain_overwrite_ms"], 0.0)
        cow_ratio = round(cow_over_64 / cow_over_1, 2)
        return {
            "object_bytes_1x": base,
            "rows": rows,
            "snap_create_wall_ratio_64x": ratio("snap_create_ms"),
            "clone_wall_ratio_64x": ratio("clone_ms"),
            "cow_overhead_ratio_64x": cow_ratio,
            "cow_vs_plain_overwrite_1x": round(
                r1["cow_overwrite_ms"] /
                max(r1["plain_overwrite_ms"], 1e-6), 2),
            # the flag — not a hard error — records the verdict
            "clone_is_ometa": bool(
                ratio("snap_create_ms") < 8.0 and
                ratio("clone_ms") < 8.0 and cow_ratio < 8.0),
        }

    return asyncio.run(run())


def multiproc_metric() -> dict:
    """Round 18: the SAME closed-loop client workload against the two
    cluster backends — every daemon in ONE interpreter vs one OS
    process per daemon, over identical localhost-TCP messengers
    (cluster/README.md). The claim the section pins: crossing the
    process boundary (real kernel scheduler, per-process interpreter)
    costs less than 2x in client ops/s (``proc_within_2x`` in the
    compact tail), and proc spawn-to-healthy stays a dev-loop cost
    (seconds, not minutes)."""
    import asyncio

    from ceph_tpu.cluster.vstart import Cluster
    from ceph_tpu.sim.loadgen import LoadGen

    async def one(backend: str) -> dict:
        t0 = time.perf_counter()
        c = await Cluster(n_mons=1, n_osds=3,
                          backend=backend).start()
        spawn_s = time.perf_counter() - t0
        try:
            await c.client.pool_create("mpbench", pg_num=16)
            await c.wait_for_clean(timeout=120)
            rep = await LoadGen(
                c, "mpbench", sessions=200, clients=8,
                ops_per_session=2, write_bytes=512,
                concurrency=64, op_timeout=60.0).run()
            assert rep["errors"] == 0, rep["error_samples"]
            return {"backend": backend,
                    "spawn_to_healthy_s": round(spawn_s, 3),
                    "ops": rep["ops"],
                    "ops_per_s": rep["ops_per_s"],
                    "p50_ms": rep["p50_ms"],
                    "p99_ms": rep["p99_ms"]}
        finally:
            await c.stop()

    async def run() -> dict:
        inproc = await one("inproc")
        proc = await one("proc")
        return {
            "inproc": inproc,
            "proc": proc,
            "ops_ratio_inproc_vs_proc": round(
                inproc["ops_per_s"] / proc["ops_per_s"], 3)
            if proc["ops_per_s"] else None,
            "proc_within_2x":
                proc["ops_per_s"] * 2 >= inproc["ops_per_s"],
        }
    return asyncio.run(run())


def _device_fault_cycle(F, devmon_mod) -> dict:
    """The injected-fault leg: quarantine entry and re-promotion,
    measured on a small interpret-mode kernel mapper (the only
    mapper that HAS a kernel path on CPU; on TPU the same env pin
    keeps the leg's compile cost bounded and deterministic)."""
    import numpy as np

    from ceph_tpu.crush import builder
    from ceph_tpu.crush.builder import TYPE_HOST
    from ceph_tpu.crush.mapper import Mapper

    prev = os.environ.get("CEPH_TPU_CRUSH_KERNEL")
    os.environ["CEPH_TPU_CRUSH_KERNEL"] = "interpret"
    try:
        cm, root = builder.build_hierarchy(4, 2)
        rid = builder.add_simple_rule(cm, root, TYPE_HOST)
        probe = Mapper(cm, config={
            "crush_kernel_reprobe_base": 0.0,
            "crush_kernel_reprobe_max": 0.0,
            "crush_kernel_reprobe_disable_after": 8})
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_CRUSH_KERNEL", None)
        else:
            os.environ["CEPH_TPU_CRUSH_KERNEL"] = prev
    xs = np.arange(256)
    out0, path0 = probe.map_pgs_path(rid, xs, 2)
    if path0 != "pallas-interpret":
        return {"skipped": f"no kernel path on this box ({path0})"}
    dm = devmon_mod.devmon()
    before = dm.perf.dump()
    inj = F.FaultInjector(seed=16)
    inj.install("bench_cycle", [
        F.jit_fail("crush_map_pgs", key="*'kern'*", count=1)])
    devmon_mod.set_fault_injector(inj)
    try:
        t0 = time.perf_counter()
        out_deg, path_deg = probe.map_pgs_path(rid, xs, 2)
        degrade_ms = (time.perf_counter() - t0) * 1e3
        served_exact = bool(
            (np.asarray(out_deg) == np.asarray(out0)).all())
        t0 = time.perf_counter()
        path_re, tries = path_deg, 0
        while probe.kernel_quarantine_info() is not None and \
                tries < 50:
            _, path_re = probe.map_pgs_path(rid, xs, 2)
            tries += 1
        repromote_ms = (time.perf_counter() - t0) * 1e3
    finally:
        devmon_mod.set_fault_injector(None)
    after = dm.perf.dump()

    def _delta(k):
        return int(after.get(k, 0)) - int(before.get(k, 0))

    return {
        "kernel_mode": "interpret",
        "degraded_path": path_deg,
        "degraded_served_bit_exact": served_exact,
        "degrade_ms": round(degrade_ms, 2),
        "repromote_ms": round(repromote_ms, 2),
        "repromoted_path": path_re,
        "quarantine_entries": _delta("quarantine_entries"),
        "quarantine_exits": _delta("quarantine_exits"),
        "probes": _delta("quarantine_probes"),
        "faults_injected": _delta("faults_injected"),
    }


def _compile_seconds() -> float:
    """Cumulative jit-compile wall observed by the device-runtime
    monitor (round 14) — the devmon counter every wrapped jit entry
    point (crush mapper/sharded sweep, EC encode/decode/fused-CRC,
    streaming pipeline) feeds on its first call per shape."""
    from ceph_tpu.utils.devmon import devmon
    d = devmon().perf.dump()
    return float(d.get("jit_compile_seconds", 0.0))


def _with_compile_split(fn, *args):
    """Run one bench section and split its wall: the returned dict
    gains ``compile_s`` — the devmon-observed jit compile seconds the
    section spent — so BENCH records can finally distinguish a compile
    regression from a runtime regression (first-call minus warm-call,
    measured rather than inferred)."""
    c0 = _compile_seconds()
    out = fn(*args)
    if isinstance(out, dict):
        out["compile_s"] = round(_compile_seconds() - c0, 3)
    return out


def main() -> None:
    c0 = _compile_seconds()
    enc, dec, stream = ec_metrics()
    ec_compile_s = round(_compile_seconds() - c0, 3)
    detail = {
        "seconds_per_step": round(enc["seconds"], 6),
        "batch": enc["batch"],
        "backend": enc["backend"],
        "platform": enc["platform"],
        "device": enc.get("device"),
        "mfu_pct": enc.get("mfu_pct"),
        "roofline_GiB/s": enc.get("roofline_GiB/s"),
        "timing": enc.get("timing"),
        "decode_GiB/s": round(dec["GiB/s"], 3),
        "decode_timing_method": dec.get("timing", {}).get("method"),
        "encode_streamed_GiB/s": round(stream["GiB/s"], 4),
        "streamed_note": "H2D inside the loop; this sandbox reaches the "
                         "TPU over a network tunnel, so the streamed row "
                         "is tunnel-bound (real-host PCIe would be "
                         "~12-16 GB/s)",
        "retraction": "round-1 value 9317 GiB/s was dispatch-timed and "
                      "invalid; this value is readback-anchored",
    }
    try:
        # resident reference = the headline encode rate; the section
        # re-measures at its own shape when the headline leg crashed
        detail["ec_streaming"] = _with_compile_split(
            ec_streaming_metric, enc.get("GiB/s"))
    except Exception:
        detail["ec_streaming_error"] = _short_err()
    try:
        detail["ec_daemon_path"] = _with_compile_split(
            ec_daemon_path_metric)
    except Exception:
        detail["ec_daemon_path_error"] = _short_err()
    # The remote compile service intermittently drops the mapper's large
    # program on the first attempt; retry once after a cooldown.
    crush = None
    for attempt in (1, 2):
        try:
            crush = _with_compile_split(crush_metric)
            detail["crush_mappings_per_s"] = crush["mappings_per_s"]
            detail["crush_detail"] = {
                k: crush[k] for k in ("n_pgs", "n_osds", "num_rep",
                                      "seconds_per_batch", "batch",
                                      "method", "seconds_100M_est",
                                      "path", "path_regressions",
                                      "path_transient",
                                      "fetches_per_sweep",
                                      "fetch_amortization",
                                      "candidate_batched",
                                      "kernel_lanes", "candidate_fold",
                                      "variants", "variants_error")
                if k in crush}
            detail.pop("crush_error", None)
            break
        except Exception:
            crush = None
            detail["crush_error"] = _short_err()
            if attempt == 1:
                time.sleep(90)
    try:
        detail["crush_multichip"] = _with_compile_split(
            crush_multichip_metric,
            crush["mappings_per_s"] if crush else None)
    except Exception:
        detail["crush_multichip_error"] = _short_err()
    try:
        detail["balancer"] = _with_compile_split(balancer_metric)
    except Exception:
        detail["balancer_error"] = _short_err()
    try:
        detail["mapping_engine"] = _with_compile_split(
            mapping_engine_metric)
    except Exception:
        detail["mapping_engine_error"] = _short_err()
    try:
        detail["mds"] = _with_compile_split(mds_metric)
    except Exception:
        detail["mds_error"] = _short_err()
    try:
        detail["tracing"] = _with_compile_split(tracing_metric)
    except Exception:
        detail["tracing_error"] = _short_err()
    try:
        detail["qos"] = _with_compile_split(qos_metric)
    except Exception:
        detail["qos_error"] = _short_err()
    try:
        detail["telemetry"] = _with_compile_split(telemetry_metric)
    except Exception:
        detail["telemetry_error"] = _short_err()
    try:
        detail["device_resilience"] = _with_compile_split(
            device_resilience_metric)
    except Exception:
        detail["device_resilience_error"] = _short_err()
    try:
        detail["tuning"] = _with_compile_split(tuning_metric)
    except Exception:
        detail["tuning_error"] = _short_err()
    try:
        detail["multiproc"] = _with_compile_split(multiproc_metric)
    except Exception:
        detail["multiproc_error"] = _short_err()
    try:
        detail["snapshot"] = _with_compile_split(snapshot_metric)
    except Exception:
        detail["snapshot_error"] = _short_err()
    print(json.dumps({
        "metric": "ec_encode_k8m3_4MiB",
        "value": round(enc["GiB/s"], 3),
        "unit": "GiB/s",
        "vs_baseline": round(enc["GiB/s"] / BASELINE_GIBS, 3),
        "detail": detail,
    }))
    # Driver-parse line (VERDICT r5 weak #8): the full record above has
    # grown past the driver's tail capture, leaving `parsed: null`.
    # Emit a compact (<500 char) metric/value/unit summary as the LAST
    # stdout line — the driver parses the tail, humans read the blob.
    print(json.dumps(compact_summary(enc, dec, detail)))


def compact_summary(enc: dict, dec: dict, detail: dict) -> dict:
    out = {
        "metric": "ec_encode_k8m3_4MiB",
        "value": round(enc["GiB/s"], 3),
        "unit": "GiB/s",
        "vs_baseline": round(enc["GiB/s"] / BASELINE_GIBS, 3),
        "decode_GiB_s": round(dec["GiB/s"], 3),
    }
    if enc.get("mfu_pct") is not None:
        out["mfu_pct"] = enc["mfu_pct"]
    if detail.get("crush_mappings_per_s") is not None:
        out["crush_mappings_per_s"] = detail["crush_mappings_per_s"]
    elif "crush_error" in detail:
        out["crush_error"] = detail["crush_error"][:120]
    mc = detail.get("crush_multichip")
    if isinstance(mc, dict):
        out["crush_100M_s"] = mc["seconds_100M"]
        out["crush_n_devices"] = mc["n_devices"]
        if mc.get("extrapolated"):
            # a smoke-size rescale must never read as the measured
            # pod wall in the driver-parsed tail
            out["crush_100M_extrapolated"] = True
    regs = detail.get("crush_detail", {}).get("path_regressions")
    if regs:                     # loud in the driver-parsed tail line
        out["crush_path_regression"] = "; ".join(regs)[:120]
    # round 15: the choose_args rate rides the compact tail — the
    # variant the 75.6k/s r05 cliff lived in, so its trajectory must
    # be driver-parsed every round, not buried in the detail blob
    ca = detail.get("crush_detail", {}).get("variants", {})
    if isinstance(ca, dict):
        ca_row = ca.get("choose_args")
        if isinstance(ca_row, dict) and \
                ca_row.get("mappings_per_s") is not None:
            out["crush_choose_args_per_s"] = ca_row["mappings_per_s"]
    qos = detail.get("qos")
    if isinstance(qos, dict):    # the round-11 QoS verdict, compact
        out["qos_protected"] = qos.get("scheduler_protects_cold")
        out["qos_p99_ratio_fifo_vs_mclock"] = [
            qos.get("fifo_p99_ratio"), qos.get("mclock_p99_ratio")]
    tel = detail.get("telemetry")
    if isinstance(tel, dict):    # the round-12 report-loop verdict
        out["telemetry_within_noise"] = tel.get(
            "telemetry_within_noise")
    ecs = detail.get("ec_streaming")
    if isinstance(ecs, dict):    # the round-13 EC aggregator verdict
        out["ec_agg_within_2x"] = ecs.get("ec_agg_within_2x")
        out["ec_agg_GiBs"] = [ecs.get("per_op_GiBs"),
                              ecs.get("aggregated_GiBs"),
                              ecs.get("pipeline_GiBs")]
    ecd = detail.get("ec_daemon_path")
    if isinstance(ecd, dict):    # the round-19 read-side verdict
        out["daemon_within_2x_resident"] = ecd.get(
            "daemon_within_2x_resident")
        out["ec_daemon_GiBs"] = [ecd.get("per_op_GiBs"),
                                 ecd.get("read_agg_GiBs"),
                                 ecd.get("resident_GiBs")]
    res = detail.get("device_resilience")
    if isinstance(res, dict):    # the round-16 fault-plane verdict
        out["resilience_within_noise"] = res.get(
            "no_fault", {}).get("resilience_within_noise")
    tun = detail.get("tuning")
    if isinstance(tun, dict):    # the round-17 self-driving verdict
        out["tuner_protects_cold"] = tun.get("tuner_protects_cold")
        out["tuner_actions"] = [tun.get("actions_committed"),
                                tun.get("actions_reverted")]
    mp = detail.get("multiproc")
    if isinstance(mp, dict):     # the round-18 process-boundary verdict
        out["proc_within_2x"] = mp.get("proc_within_2x")
        out["proc_spawn_s"] = mp.get("proc", {}).get(
            "spawn_to_healthy_s")
    snap = detail.get("snapshot")
    if isinstance(snap, dict):   # the round-20 O(metadata) snap verdict
        out["clone_is_ometa"] = snap.get("clone_is_ometa")
        out["snap_wall_ratios_64x"] = [
            snap.get("snap_create_wall_ratio_64x"),
            snap.get("clone_wall_ratio_64x"),
            snap.get("cow_overhead_ratio_64x")]
    # round 14: total observed jit-compile wall for the whole run —
    # BENCH_r06+ can split a compile regression from a runtime one
    try:
        out["compile_total_s"] = round(_compile_seconds(), 3)
    except Exception:
        pass
    # belt-and-braces: the driver's tail capture is ~2000 chars; stay
    # far inside it even if an error string sneaks in
    while len(json.dumps(out)) > 500 and len(out) > 3:
        out.pop(next(reversed(out)))
    return out


if __name__ == "__main__":
    main()
